"""Fault-tolerant run supervision for chunked ensemble exports.

The north-star workload is a 10k-observation fold-mode ensemble streamed
through :meth:`FoldEnsemble.iter_chunks` into
:func:`~psrsigsim_tpu.io.export.export_ensemble_psrfits` — a multi-hour,
multi-process run.  This module is the layer that makes that run survive
its environment:

- **Crash-safe output** — every PSRFITS file is already written
  temp-then-rename (Orbax-style atomic commit); the supervisor adds the
  durable record: per-file sha256 in an append-only fsync'd journal and,
  at finalize, in the export manifest.  ``resume="verify"`` re-hashes
  existing files against that record instead of trusting existence, so a
  torn disk or a truncated file from a previous crash is re-written, not
  silently shipped.
- **Chunk journal + atomic cursor** — one fsync'd journal line per
  committed chunk (files + hashes) and a temp+rename cursor file.  A
  SIGKILL at ANY point leaves either a committed record or none; the
  resume path re-derives everything else from hashes, so output is
  bit-identical to an uninterrupted run.
- **NaN quarantine** — the jitted chunk program returns a fused
  per-(observation, channel) finite mask (checkify-style in-graph error
  accumulation, no per-observation host round-trip).  Non-finite
  observations are quarantined in the journal, re-run once with a fresh
  fold of their PRNG key (:meth:`FoldEnsemble.run_quantized_at`), and
  recorded in the manifest if still bad — one poisoned observation costs
  one observation, never the run.
- **Degradation ladder** — the export writer pool heals itself
  (respawn-with-backoff, then in-process serial writer;
  ``io/export._WriterPool``); the supervisor records when the run
  finished degraded.

Everything is exercised by the deterministic fault-injection layer in
:mod:`psrsigsim_tpu.runtime.faults`; injection points are armed only by
an explicit :class:`~psrsigsim_tpu.runtime.faults.FaultPlan`.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import threading
import time

import numpy as np

from .faults import crash_process
from .retry import RetryPolicy

__all__ = ["RunSupervisor", "RunResult", "supervised_export",
           "ProcessSupervisor", "load_chunk_journal",
           "load_journal_records"]

_JOURNAL_NAME = "run_journal.jsonl"
_CURSOR_NAME = "run_cursor.json"

# folded into a quarantined observation's key for its single re-run: any
# fixed nonzero constant works; it only has to differ from the epoch
# folds (small ints) other derivations use
RETRY_FOLD_SALT = 0x7E7247


def load_journal_records(path, truncate=True):
    """Every valid complete record of an append-only fsync'd journal,
    in order, plus the byte length of the journal's valid prefix.

    THE shared torn-tail rule of every journal in this repo (the export
    supervisor's, the Monte-Carlo study engine's, the dataset
    factory's, the serving result cache's): a crash can leave at most
    one torn final line, which is skipped AND — when ``truncate`` —
    truncated away: appending a later run's records after a
    newline-less fragment would weld two records into one permanently
    unparseable line, silently discarding every later commit on the
    NEXT load.  Truncating costs at most one chunk's recompute.

    Returns ``(records, valid_end)``; a missing journal is ``([], 0)``.
    Callers doing open-time replay must hold whatever cross-process
    lock guards their journal (no writer may be mid-append while the
    tail is truncated) — the run journals are single-writer by
    construction, the cache holds its flock.
    """
    records = []
    valid_end = 0
    try:
        with open(path, "rb") as f:
            for line in f:
                if not line.endswith(b"\n"):
                    break  # torn mid-write: unsafe to append after
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    break
                valid_end += len(line)
                records.append(rec)
    except FileNotFoundError:
        return records, 0
    if truncate and valid_end < os.path.getsize(path):
        with open(path, "rb+") as f:
            f.truncate(valid_end)
    return records, valid_end


def load_chunk_journal(path, event="chunk", key="start"):
    """Valid committed-chunk records of an append-only fsync'd journal,
    keyed by ``int(rec[key])`` for records whose ``"e"`` equals
    ``event`` — the chunked-run view over
    :func:`load_journal_records` (one torn-tail rule in the repo)."""
    records, _ = load_journal_records(path)
    return {int(rec[key]): rec for rec in records if rec.get("e") == event}


def load_resume_hashes(out_dir, journal_path=None, truncate=True):
    """The basename -> sha256 map hash-verified resume checks committed
    export files against, rebuilt from the manifest plus the journal's
    commit records.  Returns ``(hashes, records)`` (the raw records so
    :meth:`RunSupervisor._load_previous` can replay its extra events).

    THE one hash source for resume: the leader's supervisor and the pod
    follower mirror both load through here — pod lockstep depends on
    their skip decisions deriving from the same bytes, so the loading
    rule must not be able to drift between two copies.  Followers pass
    ``truncate=False``: the live leader owns the journal file."""
    from ..io.export import _load_manifest

    hashes = {}
    man = _load_manifest(out_dir)
    if man is not None:
        hashes.update(man.get("files", {}))
    records, _ = load_journal_records(
        journal_path or os.path.join(out_dir, _JOURNAL_NAME),
        truncate=truncate)
    for rec in records:
        if rec.get("e") == "commit":
            hashes.update(rec.get("files", {}))
    return hashes, records


def file_done_check(path, hashes, verify, verified):
    """THE per-file resume predicate: existence under plain resume;
    existence + sha256 match against ``hashes`` under ``verify``
    (unknown or mismatched hashes mean "rewrite it").  Paths proven ok
    are remembered in the caller-owned ``verified`` set so chunk-skip /
    per-file / group predicates don't re-hash multi-GB outputs.  Shared
    by :meth:`RunSupervisor.file_ok` and the pod follower mirror — the
    definition of "done" must be a single point of truth."""
    if path in verified:
        return True
    if not os.path.exists(path):
        return False
    if not verify:
        verified.add(path)
        return True
    from ..io.export import _file_sha

    want = hashes.get(os.path.basename(path))
    if want is not None and _file_sha(path) == want:
        verified.add(path)
        return True
    return False


class RunResult:
    """What a supervised export run produced.

    Attributes
    ----------
    paths : list[str]
        Every output file path of the export (finished or quarantined).
    quarantined : list[int]
        Observations that stayed non-finite after their retry; their
        files are NOT written and the manifest records them.
    retried : list[int]
        Observations the NaN guard quarantined and re-ran.
    recovered : list[int]
        The subset of ``retried`` whose re-run came back finite.
    degraded : bool
        True when the writer pool fell back to the serial writer.
    hashes : dict[str, str]
        basename -> sha256 for every committed file.
    pipeline : dict or None
        The export's stage-telemetry snapshot (the manifest's
        ``pipeline`` key): per-stage busy seconds, fetched bytes, queue
        depths, and the named bottleneck stage.
    integrity : dict or None
        The run's integrity counters (the manifest's ``integrity``
        key) when the checksum lattice was armed: checks, checksum/
        audit mismatches, healed chunks, and the ``sdc_suspect`` flag.
    """

    def __init__(self, paths, quarantined, retried, recovered, degraded,
                 hashes, out_dir, pipeline=None, integrity=None):
        self.paths = list(paths)
        self.quarantined = sorted(quarantined)
        self.retried = sorted(retried)
        self.recovered = sorted(recovered)
        self.degraded = bool(degraded)
        self.hashes = dict(hashes)
        self.out_dir = out_dir
        self.pipeline = pipeline
        self.integrity = integrity

    def __repr__(self):
        return (f"RunResult(files={len(self.paths)}, "
                f"quarantined={self.quarantined}, retried={self.retried}, "
                f"degraded={self.degraded})")


class RunSupervisor:
    """Journal/quarantine/verify state machine for one supervised export.

    Wire-up: :func:`export_ensemble_psrfits` calls :meth:`file_ok` for
    resume decisions, :meth:`observe_chunk` on every fetched finite mask,
    and :meth:`chunk_committed` when a chunk's files are durably written
    (from the writer pool's FIFO drain or directly after serial writes);
    the retry phase reports through :meth:`record_retry`.  Tests drive
    the same machine through :func:`supervised_export`.
    """

    def __init__(self, out_dir, resume=True, verify=False, faults=None,
                 retry=True, retry_fold_salt=RETRY_FOLD_SALT):
        self.out_dir = str(out_dir)
        os.makedirs(self.out_dir, exist_ok=True)
        self.verify = bool(verify)
        self.faults = faults
        self.retry_enabled = bool(retry)
        self.retry_fold_salt = int(retry_fold_salt)
        self.journal_path = os.path.join(self.out_dir, _JOURNAL_NAME)
        self.cursor_path = os.path.join(self.out_dir, _CURSOR_NAME)
        self._journal_f = None
        self._hashes = {}        # basename -> sha256 of committed files
        self._verified = set()   # paths already proven ok THIS run
        self._quarantined = set()  # ever flagged non-finite this run
        self._rfi_obs = {}       # global obs id -> contaminated cell count
        self._retried = set()
        self._recovered = set()
        self._still_bad = set()
        self._degraded = False
        self._commits = 0
        if not resume:
            for p in (self.journal_path, self.cursor_path):
                try:
                    os.unlink(p)
                except FileNotFoundError:
                    pass
        else:
            self._load_previous()

    # -- resume state ------------------------------------------------------

    def _load_previous(self):
        """Rebuild the hash record from the manifest and the journal —
        replayed through the repo's ONE torn-tail loader
        (:func:`load_journal_records`): a newline-less tail from a
        crash is skipped and truncated, costing at most one chunk's
        re-verify."""
        hashes, records = load_resume_hashes(self.out_dir,
                                             self.journal_path)
        self._hashes.update(hashes)
        for rec in records:
            if rec.get("e") in ("rfi", "rfi_retry"):
                # replay the scenario-truth record so a resumed
                # export's manifest summary stays COMPLETE (the
                # skipped committed chunks never re-observe)
                for i, c in zip(rec.get("obs", ()),
                                rec.get("cells", ())):
                    if c:
                        self._rfi_obs[int(i)] = int(c)
                    else:
                        self._rfi_obs.pop(int(i), None)

    # -- exporter hooks ----------------------------------------------------

    def file_ok(self, path):
        """Is this output file already done?  Existence under plain
        resume; existence + sha256 match under ``verify`` (unknown or
        mismatched hashes mean "rewrite it").

        A path proven ok once this run — verified here, or committed by
        this run's writers — is remembered, so the chunk-skip, per-file
        and group predicates don't re-hash multi-GB outputs two or three
        times each.  (Delegates to :func:`file_done_check`, the single
        definition of "done" the pod follower mirror also uses.)"""
        return file_done_check(path, self._hashes, self.verify,
                               self._verified)

    def poisoned_noise_norms(self, n_obs, noise_norms, default=1.0):
        """Apply the ``nan.obs`` injection point (tests only): NaN the
        configured observations' noise norms so non-finite data flows
        through the REAL pipeline and guard.  The clean array is what the
        manifest fingerprints and what the retry pass uses."""
        if self.faults is None:
            return noise_norms
        cfg = self.faults.config("nan.obs")
        if cfg is None:
            return noise_norms
        idx = np.asarray(cfg.get("indices", ()), np.int64)
        if idx.size == 0:
            return noise_norms
        if noise_norms is None:
            norms = np.full(n_obs, float(default), np.float64)
        else:
            norms = np.array(noise_norms, np.float64, copy=True)
        norms[idx] = np.nan
        return norms

    def observe_chunk(self, start, finite):
        """Digest one chunk's in-graph finite mask ``(count, Nchan)``:
        quarantine every observation with any non-finite channel, journal
        the event, and return the newly bad global ids."""
        finite = np.asarray(finite)
        bad_rows = np.where(~finite.all(axis=tuple(range(1, finite.ndim))))[0]
        out = set()
        for j in bad_rows:
            i = start + int(j)
            out.add(i)
            self._quarantined.add(i)
            self._append_journal({
                "e": "quarantine", "obs": i,
                "bad_chans": int((~finite[j]).sum())})
        if out:
            self._sync_journal()
        return out

    def observe_rfi(self, start, mask):
        """Digest one chunk's in-graph ground-truth RFI mask ``(count,
        Nchan, nsub)`` from the scenario engine: journal which
        observations carry injected RFI and how many (channel, subint)
        cells it touches — provenance, not quarantine (the contamination
        is intentional physics; nothing re-runs).  Rides the same
        fsync'd append-only journal as the finite guard, so a resumed
        export keeps a complete contamination record."""
        mask = np.asarray(mask)
        hit = np.where(mask.any(axis=tuple(range(1, mask.ndim))))[0]
        fresh = []
        for j in hit:
            i = start + int(j)
            cells = int(mask[j].sum())
            if self._rfi_obs.get(i) == cells:
                continue  # a resumed chunk re-observing the same truth
            self._rfi_obs[i] = cells
            fresh.append((i, cells))
        if fresh:
            self._append_journal({
                "e": "rfi", "start": int(start),
                "obs": [i for i, _ in fresh],
                "cells": [c for _, c in fresh]})
            self._sync_journal()

    def observe_rfi_retry(self, indices, mask):
        """Overwrite the RFI truth for re-folded observations: a healed
        (``fold_salt``) re-run draws a FRESH realization, so the main
        pass's record for these observations is stale — the journal and
        manifest must follow the bytes actually delivered.  ``mask`` rows
        align with ``indices``; zero contaminated cells DELETES the
        entry (the healed draw may carry no RFI at all).  Also used to
        drop the record of still-bad observations whose files are not
        written."""
        mask = np.asarray(mask) if mask is not None else None
        changed = []
        for j, i in enumerate(indices):
            i = int(i)
            cells = int(mask[j].sum()) if mask is not None else 0
            prev = self._rfi_obs.get(i)
            if cells == 0:
                if prev is None:
                    continue
                del self._rfi_obs[i]
            else:
                if prev == cells:
                    continue
                self._rfi_obs[i] = cells
            changed.append((i, cells))
        if changed:
            self._append_journal({
                "e": "rfi_retry",
                "obs": [i for i, _ in changed],
                "cells": [c for _, c in changed]})
            self._sync_journal()

    def chunk_committed(self, token, results):
        """A chunk's files are durably on disk: record their hashes in
        the append-only journal (fsync'd — THE crash-safe record), then
        advance the atomic cursor.  ``token`` is the exporter's
        ``(kind, ident, paths)`` tag; ``results`` is
        ``[(path, sha_or_None), ...]`` from the writers."""
        files = {os.path.basename(p): sha for p, sha in results
                 if sha is not None}
        self._hashes.update(files)
        self._verified.update(p for p, _ in results)
        kind, ident = token[0], token[1]
        self._append_journal({"e": "commit", "kind": kind, "ident": ident,
                              "files": files})
        self._sync_journal()
        self._commits += 1
        self._write_cursor()
        if self.faults is not None:
            # disk.bitrot injection: decay a just-committed file AFTER
            # its sha256 became the durable record — exactly what the
            # scrub layer exists to find (tests only)
            from .integrity import maybe_bitrot

            for p, _sha in results:
                maybe_bitrot(self.faults, p)
        self._maybe_kill(kind, ident)

    def record_retry(self, group, retried, still_bad):
        """The retry phase's verdict for one file/group: which
        observations were re-run, and which stayed non-finite."""
        self._retried.update(retried)
        self._recovered.update(i for i in retried if i not in still_bad)
        self._still_bad.update(still_bad)
        self._append_journal({
            "e": "retry", "group": int(group),
            "obs": [int(i) for i in retried],
            "still_bad": [int(i) for i in still_bad]})
        self._sync_journal()

    def record_integrity(self, kind, start, obs=(), healed=True,
                         detail=None):
        """Durable record of one integrity event (``kind`` is
        ``"checksum"`` — the lattice caught a fetch-window corruption —
        or ``"audit"`` — duplicate execution caught the device
        disagreeing with itself): which chunk, which observations, and
        whether verified re-execution healed it.  Rides the same
        fsync'd append-only journal as every other durable claim, so a
        resumed run (and the operator) sees the full corruption
        history."""
        rec = {"e": "integrity", "kind": str(kind), "start": int(start),
               "obs": [int(i) for i in obs], "healed": bool(healed)}
        if detail:
            rec["detail"] = dict(detail)
        self._append_journal(rec)
        self._sync_journal()

    def note_degraded(self):
        self._degraded = True
        self._append_journal({"e": "degraded"})
        self._sync_journal()

    def quarantined_indices(self):
        return set(self._quarantined)

    # -- journal / cursor plumbing ----------------------------------------

    def _append_journal(self, rec):
        if self._journal_f is None:
            self._journal_f = open(self.journal_path, "a")
        self._journal_f.write(json.dumps(rec, sort_keys=True) + "\n")

    def _sync_journal(self):
        if self._journal_f is not None:
            self._journal_f.flush()
            os.fsync(self._journal_f.fileno())

    def _write_cursor(self):
        """Atomic cursor: commit count + journal byte offset — a SIGKILL
        leaves the old cursor or the new one, never a torn file."""
        from ..io.export import _atomic_write_json

        pos = self._journal_f.tell() if self._journal_f is not None else 0
        _atomic_write_json(self.cursor_path,
                           {"commits": self._commits, "journal_bytes": pos})

    def _maybe_kill(self, kind, ident):
        """``run.kill`` injection point: SIGKILL the exporting process
        right after the configured commit — the preempted-host scenario
        for kill/resume tests.  ``after_start`` matches the chunk start
        (one-obs-per-file exports) or the group index (packed exports:
        ``kind`` "group"/"groups") — a target the commit stream can never
        reach must not silently disarm a fault test by construction, so
        both token families participate.  Marker-file once-semantics keep
        the resume run alive."""
        if self.faults is None:
            return
        cfg = self.faults.config("run.kill")
        if cfg is None:
            return
        after = cfg.get("after_start")
        idents = list(ident) if isinstance(ident, (list, tuple)) else [ident]
        if after is not None and not (
                kind in ("chunk", "group", "groups") and after in idents):
            return
        if self.faults.fire("run.kill", token=f"start={idents[0]}"):
            crash_process()

    # -- finalize ----------------------------------------------------------

    def close(self):
        """Release the journal handle (idempotent).  The failure path of
        :func:`supervised_export` calls this so a driver looping over
        failed runs does not accumulate leaked fds; everything recorded
        so far is already durable (appends are fsync'd per commit)."""
        if self._journal_f is not None:
            self._journal_f.close()
            self._journal_f = None

    def finalize(self, paths):
        """Fold the run's durable record into the manifest (atomic
        rewrite), close the journal, and summarize."""
        from ..io.export import _load_manifest, _write_manifest

        man = _load_manifest(self.out_dir) or {}
        man["files"] = dict(sorted(self._hashes.items()))
        man["quarantined"] = sorted(int(i) for i in self._still_bad)
        if self._rfi_obs:
            # scenario provenance: how much injected RFI the dataset
            # carries (per-observation detail lives in the journal)
            man["rfi"] = {
                "obs_with_rfi": len(self._rfi_obs),
                "contaminated_cells": int(sum(self._rfi_obs.values())),
            }
        _write_manifest(self.out_dir, man)
        self.close()
        return RunResult(paths, self._still_bad, self._retried,
                         self._recovered, self._degraded, self._hashes,
                         self.out_dir, pipeline=man.get("pipeline"),
                         integrity=man.get("integrity"))


class ProcessSupervisor:
    """Keep one subprocess alive: spawn, watch, restart with backoff.

    The process-level sibling of the export writer pool's self-healing
    loop, grown for the serving fleet: a replica that dies (OOM kill,
    preemption, a ``replica.kill`` chaos shot) is restarted under a
    :class:`~psrsigsim_tpu.runtime.retry.RetryPolicy` — jittered, so a
    fleet respawning after a shared outage does not restart in lockstep
    — and a replica that keeps dying faster than ``healthy_after_s``
    exhausts the policy's attempt budget and is marked ``failed``
    instead of flapping forever (the bounded-respawn discipline the
    writer pool established; an unbounded respawn loop amplifies the
    outage it is supposed to absorb).

    Parameters
    ----------
    name : str
        Label for introspection/logging.
    spawn : callable
        Zero-argument callable returning a started
        :class:`subprocess.Popen`.  Called for the initial start and
        for every restart.
    policy : RetryPolicy, optional
        Restart backoff budget.  ``max_attempts`` bounds CONSECUTIVE
        unhealthy deaths; a child that stayed up ``healthy_after_s``
        resets the counter.  Default: 5 attempts, 0.05 s base, jittered.
    healthy_after_s : float
        Uptime after which a death counts as fresh (resets backoff).
    on_spawn, on_exit : callable, optional
        ``on_spawn(supervisor, proc)`` after every (re)spawn;
        ``on_exit(supervisor, returncode)`` after every child death
        (restart decisions already made) — the fleet uses these to
        re-wire routing to the replacement's new port.
    """

    def __init__(self, name, spawn, policy=None, healthy_after_s=5.0,
                 on_spawn=None, on_exit=None):
        self.name = str(name)
        self._spawn = spawn
        self.policy = policy if policy is not None else RetryPolicy(
            max_attempts=5, base_delay=0.05, max_delay=2.0, jitter=0.5)
        self.healthy_after_s = float(healthy_after_s)
        self._on_spawn = on_spawn
        self._on_exit = on_exit
        self._lock = threading.Lock()
        self._proc = None
        self._stopping = False
        self.failed = False
        self.restarts = 0
        self._consecutive_deaths = 0
        self._spawned_at = 0.0
        self._watcher = None

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Spawn the child and the watcher thread.  Idempotent on the
        WATCHER, not the child: while a watcher is alive (child running
        OR dead-and-in-backoff) a re-invocation is a no-op — a second
        watcher would double-count every death and leak an unsupervised
        child.  A fresh start (never started / stopped / failed) resets
        the death budget."""
        with self._lock:
            if self._watcher is not None and self._watcher.is_alive():
                return self
            self._stopping = False
            self.failed = False
            self._consecutive_deaths = 0
            self._respawn_locked()
            self._watcher = threading.Thread(
                target=self._watch, daemon=True,
                name=f"pss-supervise-{self.name}")
            self._watcher.start()
        return self

    def _respawn_locked(self):
        self._proc = self._spawn()
        self._spawned_at = time.monotonic()
        if self._on_spawn is not None:
            self._on_spawn(self, self._proc)

    def _watch(self):
        while True:
            with self._lock:
                proc = self._proc
            if proc is None:
                return
            rc = proc.wait()
            uptime = time.monotonic() - self._spawned_at
            with self._lock:
                if self._stopping:
                    return
                if self._on_exit is not None:
                    self._on_exit(self, rc)
                if uptime >= self.healthy_after_s:
                    self._consecutive_deaths = 0
                self._consecutive_deaths += 1
                if self._consecutive_deaths >= self.policy.max_attempts:
                    self.failed = True
                    self._proc = None
                    return
                d = self.policy.delay(self._consecutive_deaths - 1)
            if d > 0:
                time.sleep(d)
            with self._lock:
                if self._stopping:
                    return
                # count at respawn START: a restart in progress (the
                # replacement may take seconds to boot) is a restart
                self.restarts += 1
                self._respawn_locked()

    # -- control -----------------------------------------------------------

    def kill(self, sig=signal.SIGKILL):
        """Send ``sig`` to the child (chaos shots use SIGKILL); the
        watcher then restarts it under the policy."""
        with self._lock:
            proc = self._proc
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass

    def restart(self, sig=signal.SIGTERM, kill_after_s=30.0):
        """GRACEFUL restart: send ``sig`` (drain) and let the watcher
        respawn the child when it exits — in-flight work finishes, then
        the process is replaced.  A child that ignores the drain signal
        is SIGKILLed after ``kill_after_s`` (the gray-failure case this
        exists for: a wedged replica may be too sick to honor SIGTERM).
        Non-blocking; the escalation runs on a daemon thread."""
        with self._lock:
            proc = self._proc
        if proc is None or proc.poll() is not None:
            return
        self.kill(sig)

        def _escalate():
            try:
                proc.wait(kill_after_s)
            except subprocess.TimeoutExpired:
                try:
                    proc.kill()
                except (ProcessLookupError, OSError):
                    pass

        threading.Thread(target=_escalate, daemon=True,
                         name=f"pss-restart-{self.name}").start()

    def stop(self, sig=signal.SIGTERM, timeout=30.0):
        """Orchestrated shutdown: no restart, ``sig`` (drain) first,
        SIGKILL after ``timeout``.  Returns the child's returncode (None
        if it was never running)."""
        with self._lock:
            self._stopping = True
            proc = self._proc
        if proc is None:
            return None
        if proc.poll() is None:
            try:
                proc.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass
            try:
                proc.wait(timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        if self._watcher is not None and self._watcher is not threading.current_thread():
            self._watcher.join(timeout)
        return proc.returncode

    # -- introspection -----------------------------------------------------

    @property
    def proc(self):
        with self._lock:
            return self._proc

    @property
    def pid(self):
        with self._lock:
            return self._proc.pid if self._proc is not None else None

    def alive(self):
        with self._lock:
            return (not self.failed and self._proc is not None
                    and self._proc.poll() is None)

    def __repr__(self):
        state = ("failed" if self.failed
                 else "alive" if self.alive() else "down")
        return (f"ProcessSupervisor({self.name!r}, {state}, "
                f"restarts={self.restarts})")


def supervised_export(ens, n_obs, out_dir, template, pulsar, *,
                      resume=True, faults=None, retry=True, **export_kw):
    """Run a chunked ensemble export under full supervision.

    A drop-in upgrade of
    :func:`~psrsigsim_tpu.io.export.export_ensemble_psrfits` that layers
    on the fault-tolerant run loop (module docstring): per-file sha256
    journaling, hash-verified resume, the in-graph NaN quarantine with a
    single salted retry, and the chunk journal that makes a SIGKILL at
    any point resumable to bit-identical output.

    Args:
        resume: ``True`` (skip files recorded as done), ``False`` (start
            clean — journal and cursor are reset), or ``"verify"``
            (re-hash every existing file against the journal/manifest
            record and rewrite any that fail — the mode for resuming
            after an unclean death on shared storage).
        faults: optional :class:`~psrsigsim_tpu.runtime.faults.FaultPlan`
            (tests only).
        retry: re-run quarantined observations once with a fresh key
            fold; ``False`` records them as bad immediately.
        **export_kw: forwarded to ``export_ensemble_psrfits`` (seed, dms,
            noise_norms, chunk_size, writers, obs_per_file,
            ``integrity=`` — the silent-corruption defense of
            :mod:`psrsigsim_tpu.runtime.integrity`, which needs exactly
            this supervised path for its durable event journal — ...).

    Returns:
        :class:`RunResult`.
    """
    from ..io.export import export_ensemble_psrfits

    verify = resume == "verify"
    sup = RunSupervisor(out_dir, resume=bool(resume), verify=verify,
                        faults=faults, retry=retry)
    try:
        paths = export_ensemble_psrfits(
            ens, n_obs, out_dir, template, pulsar, resume=bool(resume),
            supervisor=sup, faults=faults, **export_kw)
    except BaseException:
        # the journal is already durable (fsync per commit) — just don't
        # leak its fd to drivers that loop over failing runs
        sup.close()
        raise
    return sup.finalize(paths)
