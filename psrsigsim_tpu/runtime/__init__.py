"""Fault-tolerant run supervision: the robustness backbone of bulk runs.

- :mod:`~psrsigsim_tpu.runtime.supervisor` — the resumable, self-healing
  run loop around the chunked ensemble -> PSRFITS export path
  (:func:`supervised_export` / :class:`RunSupervisor`): crash-safe
  journaled output with sha256-verified resume, in-graph NaN quarantine
  with salted retry, and an append-only chunk journal + atomic cursor —
  plus :class:`ProcessSupervisor`, the keep-one-subprocess-alive loop
  (restart with jittered backoff, bounded flapping) the serving fleet
  builds its replica supervision on.
- :mod:`~psrsigsim_tpu.runtime.retry` — capped exponential backoff
  shared by every self-healing loop (writer-pool respawn, retries).
- :mod:`~psrsigsim_tpu.runtime.faults` — deterministic, explicitly-armed
  fault injection (named points, cross-process once-semantics) so all of
  the above is exercised by tests instead of by outages.
- :mod:`~psrsigsim_tpu.runtime.telemetry` — per-stage timers for the
  streaming export pipeline (dispatch/fetch/encode/write, queue depths,
  bytes), accumulated into the export manifest and the bench report.
- :mod:`~psrsigsim_tpu.runtime.integrity` — the silent-corruption
  defense: in-graph checksum lattice (device-attested chunk digests),
  deterministic duplicate-execution SDC audits, and the self-healing
  scrub over every durable tier, with the ``device.sdc`` /
  ``host.corrupt`` / ``disk.bitrot`` fault points proving detection
  end to end.
- :mod:`~psrsigsim_tpu.runtime.programs` — the shared program registry:
  one geometry-keyed compiled-artifact store (build counts, compile
  telemetry, persistent-compilation-cache wiring) that the ensemble,
  Monte-Carlo, export, and serving program families all resolve through
  instead of holding private jit caches.
- :mod:`~psrsigsim_tpu.runtime.dist` — the multi-host pod runtime:
  ``jax.distributed`` coordinator bootstrap with a byte-identical
  single-process fallback, pod-safe global-array staging/fetch
  (:func:`put_sharded` / pod ``device_get``), the leader-rooted control
  channel with its peer-death watchdog, and the topology fingerprints
  the program registry and persistent compilation cache key on.
"""

from .dist import (PodChannel, PodInfo, PodPeerLost, device_get, init_pod,
                   is_leader, is_pod, pod_info, pod_key, put_sharded,
                   shutdown_pod)
from .faults import FaultPlan
from .integrity import (IntegrityChecker, IntegrityError,
                        resolve_integrity, scrub_dataset_dir,
                        scrub_export_dir, scrub_mc_dir)
from .programs import ProgramRegistry, enable_compilation_cache, \
    global_registry
from .retry import RetriesExhausted, RetryPolicy, call_with_retry
from .supervisor import (ProcessSupervisor, RunResult, RunSupervisor,
                         load_chunk_journal, load_journal_records,
                         supervised_export)
from .telemetry import StageTimers

__all__ = [
    "FaultPlan",
    "PodChannel",
    "PodInfo",
    "PodPeerLost",
    "init_pod",
    "pod_info",
    "pod_key",
    "is_pod",
    "is_leader",
    "put_sharded",
    "device_get",
    "shutdown_pod",
    "IntegrityChecker",
    "IntegrityError",
    "resolve_integrity",
    "scrub_export_dir",
    "scrub_mc_dir",
    "scrub_dataset_dir",
    "load_chunk_journal",
    "load_journal_records",
    "ProgramRegistry",
    "RetryPolicy",
    "RetriesExhausted",
    "StageTimers",
    "call_with_retry",
    "enable_compilation_cache",
    "global_registry",
    "ProcessSupervisor",
    "RunResult",
    "RunSupervisor",
    "supervised_export",
]
