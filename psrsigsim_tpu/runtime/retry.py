"""Capped exponential backoff for self-healing host-side loops.

The run supervisor and the export writer pool share one retry idiom:
attempt, back off exponentially up to a cap, give up after a bounded
number of attempts and let the caller degrade (pool -> serial writer,
retry -> quarantine record).  Centralizing it here keeps the policy
testable in isolation and the call sites honest about their bounds —
an unbounded `while True: respawn()` is exactly the failure amplifier
a multi-hour 10k-observation export cannot afford.

Host-only module: nothing here may touch JAX (psrlint keeps it out of
the device-module scope).
"""

from __future__ import annotations

import time

__all__ = ["RetryPolicy", "call_with_retry", "RetriesExhausted"]


class RetriesExhausted(RuntimeError):
    """All attempts of :func:`call_with_retry` failed.

    The last underlying exception is chained as ``__cause__`` and kept
    on :attr:`last_error`; :attr:`attempts` records how many were made.
    """

    def __init__(self, attempts, last_error):
        self.attempts = int(attempts)
        self.last_error = last_error
        super().__init__(
            f"gave up after {attempts} attempt(s); last error: "
            f"{last_error!r}")


class RetryPolicy:
    """Capped exponential backoff schedule, optionally jittered.

    ``delay(k)`` is the sleep before retry ``k`` (0-based):
    ``min(max_delay, base_delay * multiplier**k)``.  ``max_attempts``
    bounds the total number of attempts (first try included); the
    policy object is immutable and shareable across call sites.

    ``permanent_on`` (a tuple of exception types, default empty)
    classifies errors: an exception matching it is PERMANENT — retrying
    cannot help — and :func:`call_with_retry` re-raises it immediately
    instead of burning the backoff budget on it.  The canonical case is
    :class:`~psrsigsim_tpu.runtime.integrity.IntegrityError`: a
    corruption that survived its one verified re-execution already has
    two independent executions disagreeing, so a retry loop treating it
    like a flaky writer would just re-prove the disagreement slowly
    while the audit evidence went stale.  Transient-vs-permanent is the
    policy's call, not the loop's: every call site sharing a policy
    shares one classification.

    ``jitter`` (0..1, default 0 = exactly the deterministic schedule)
    spreads each delay uniformly over the bounded band
    ``[d*(1-jitter), min(max_delay, d*(1+jitter))]`` around the
    deterministic value ``d``.  A fleet of replicas/writers respawning
    after a shared outage otherwise backs off in lockstep and
    thundering-herds whatever shared resource (the cache lock, the
    device) killed them in the first place; successive draws from each
    process's own ``rng`` stream decorrelate the herd while the band
    keeps every delay within a tested bound of the schedule.  ``rng`` is
    an injectable zero-argument callable returning floats in ``[0, 1)``
    (e.g. ``random.Random(seed).random``) so tests replay schedules
    exactly; jitter without an rng falls back to a private
    ``random.Random`` seeded from ``os.urandom``.
    """

    def __init__(self, max_attempts=3, base_delay=0.5, max_delay=30.0,
                 multiplier=2.0, jitter=0.0, rng=None, permanent_on=()):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if base_delay < 0 or max_delay < 0:
            raise ValueError("delays must be >= 0")
        if multiplier < 1.0:
            raise ValueError("multiplier must be >= 1")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.permanent_on = tuple(permanent_on)
        if rng is None and self.jitter > 0.0:
            import random

            rng = random.Random().random
        self._rng = rng

    def delay(self, retry_index):
        """Backoff before the ``retry_index``-th retry (0-based)."""
        d = min(self.max_delay,
                self.base_delay * self.multiplier ** retry_index)
        if self.jitter == 0.0 or self._rng is None:
            return d
        lo = d * (1.0 - self.jitter)
        hi = min(self.max_delay, d * (1.0 + self.jitter))
        return lo + self._rng() * (hi - lo)

    def is_permanent(self, err):
        """Error classification: True means retrying cannot help and the
        caller must fail fast (with whatever evidence the error
        carries) instead of spending the backoff budget."""
        return isinstance(err, self.permanent_on)

    def delays(self):
        """The full schedule: one delay per retry (``max_attempts - 1``)."""
        return [self.delay(k) for k in range(self.max_attempts - 1)]

    def __repr__(self):
        return (f"RetryPolicy(max_attempts={self.max_attempts}, "
                f"base_delay={self.base_delay}, max_delay={self.max_delay}, "
                f"multiplier={self.multiplier}, jitter={self.jitter})")


def call_with_retry(fn, policy=None, retry_on=(Exception,), on_retry=None,
                    sleep=time.sleep):
    """Call ``fn()`` under ``policy``, retrying on ``retry_on``.

    ``on_retry(attempt_index, error, delay)`` is invoked before each
    backoff sleep — call sites log/count there.  Raises
    :class:`RetriesExhausted` (with the last error chained) once the
    attempt budget is spent.  ``sleep`` is injectable so tests run the
    schedule without wall-clock cost.

    Errors the policy classifies PERMANENT (``policy.is_permanent``)
    are re-raised immediately — no backoff, no further attempts: the
    evidence they carry (an integrity mismatch's audit trail) reaches
    the operator fresh instead of after a spent retry budget.
    """
    policy = policy or RetryPolicy()
    last = None
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except retry_on as err:  # noqa: PERF203 — retry loop by design
            if policy.is_permanent(err):
                raise
            last = err
            if attempt == policy.max_attempts - 1:
                break
            d = policy.delay(attempt)
            if on_retry is not None:
                on_retry(attempt, err, d)
            if d > 0:
                sleep(d)
    raise RetriesExhausted(policy.max_attempts, last) from last
