"""One program registry for the whole repo: geometry-keyed compiled
artifacts with build/compile-count telemetry.

Before this module, four subsystems each rolled their own program
caching: the serving layer AOT-compiled per (geometry, width) in
``serve/programs.py`` (the right shape — warmup, retrace guards,
persistent-cache wiring), while ensemble chunk programs, Monte-Carlo
trial programs, and the export path's packed-quantized programs each
held private ``jit`` caches keyed by Python object identity — so two
:class:`~psrsigsim_tpu.parallel.FoldEnsemble` objects over the SAME
geometry re-traced (and on first dispatch re-compiled) every program,
and nothing counted it.  This registry is the shared resolution point:

* ``get_or_build(key, builder)`` — one compiled/jitted artifact per
  hashable key, built exactly once per process (thread-safe, losers of a
  concurrent build race keep the winner's artifact), with per-key build
  counts and cumulative build seconds.
* :func:`global_registry` — the process-wide instance the ensemble, MC,
  and export program families resolve through (the serving layer's
  :class:`psrsigsim_tpu.serve.ProgramRegistry` composes a private
  instance so its per-service single-compile guard keeps meaning, same
  class, same telemetry shape).
* :func:`enable_compilation_cache` — JAX persistent-compilation-cache
  wiring (moved here from ``serve/programs.py``; serve re-exports), so
  ANY consumer can bound restart cold-start with an on-disk artifact
  store shared across processes and replicas.
* Telemetry: :meth:`ProgramRegistry.attach_timers` points the registry
  at a :class:`~psrsigsim_tpu.runtime.telemetry.StageTimers`; every
  build then lands one ``"compile"``-stage sample there, and
  :meth:`snapshot` is folded into export manifests / bench JSON so
  every run records how many programs it actually built.

Keys are ordinary hashable tuples.  By convention the first element
names the program family (``"ensemble_fold"``, ``"mc_trial"``,
``"serve_bucket"``, ...) and the rest is the geometry that shapes the
compiled program — static configs, mesh, scenario stack, width — and
NOTHING that is merely traced (profiles, DMs, keys), so sharing is
correct by construction.
"""

from __future__ import annotations

import threading
import time

__all__ = ["ProgramRegistry", "global_registry", "enable_compilation_cache",
           "trace_env_key", "donation_enabled"]


def donation_enabled():
    """Should the chunked hot-loop programs donate their per-chunk
    index/key input buffers (``jit(donate_argnums=...)``)?  Donation
    lets XLA alias a dying input's HBM into the outputs, so pod-scale
    batches don't double-buffer — values are unchanged by construction
    (pinned donation-on vs -off by tests/test_pod.py).

    ``PSS_DONATE``: ``1`` forces on, ``0`` forces off, unset/``auto``
    enables it exactly where it pays — accelerator backends (the CPU
    backend ignores donation, and the default keeps CPU test programs
    byte-for-byte the pre-donation ones)."""
    import os

    v = os.environ.get("PSS_DONATE", "auto").strip().lower()
    if v in ("1", "on", "true", "yes"):
        return True
    if v in ("0", "off", "false", "no"):
        return False
    if v in ("", "auto"):
        import jax

        return jax.default_backend() != "cpu"
    raise ValueError(f"PSS_DONATE={v!r}: use 1, 0, or auto")


def trace_env_key():
    """The trace-time environment knobs that change what a compiled
    program COMPUTES (ops/stats.py reads them while tracing) or how it
    is BUILT: the sampler backend selector, the exact-chi2 escape
    hatch, the exact-shift escape hatch, the buffer-donation switch
    (:func:`donation_enabled` — donated programs alias their inputs,
    so a flipped switch must resolve a fresh build), and the pod
    topology (:func:`psrsigsim_tpu.runtime.dist.pod_key` — a program
    compiled for a single-host mesh must never be served to a pod, and
    every process of one pod must resolve identical, process-id-
    independent keys).  Every registry key for a device program must
    include this tuple — per-instance jit caches died with their
    instances, so a flipped env var used to get a fresh trace for free;
    the process-global registry must key on it explicitly or it would
    silently serve programs traced under the old settings.

    The key is captured at CONSTRUCTION time while jit traces lazily at
    first dispatch — so the documented contract for these variables
    ("set them before building pipelines", README configuration table)
    is load-bearing: flipping one between constructing a pipeline and
    first running it is undefined (pre-registry builds traced whatever
    was set at first dispatch; registry builds honor what was set at
    construction)."""
    import os

    from .dist import pod_key

    return (os.environ.get("PSS_SAMPLER", "auto"),
            bool(os.environ.get("PSS_EXACT_CHI2")),
            bool(os.environ.get("PSS_EXACT_SHIFT")),
            donation_enabled(),
            pod_key())


def enable_compilation_cache(path):
    """Point JAX's persistent compilation cache at ``path`` (created by
    JAX on first write).  Returns True when the option stuck — older/newer
    JAX spellings are tried in order and absence is non-fatal (callers
    still work; restarts just pay compiles again).

    Under a pod the cache lands in a per-host-count subdirectory of
    ``path`` (:func:`~psrsigsim_tpu.runtime.dist.compile_cache_path`):
    single-host and pod artifacts never share a directory, and every
    host of one pod warms from the SAME store — a joining host's warmup
    is a disk read, not a compile (gated by ``bench.py --pod-smoke``)."""
    import jax

    from .dist import compile_cache_path

    path = compile_cache_path(path)
    ok = False
    try:
        jax.config.update("jax_compilation_cache_dir", str(path))
        ok = True
    except AttributeError:  # pragma: no cover - config name drift
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc)
            _cc.set_cache_dir(str(path))
            ok = True
        except Exception:
            return False
    # cache even instant compiles: the programs are small on CPU test
    # geometries but the REAL cost this exists for is TPU warmup
    for opt, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                     ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(opt, val)
        except Exception:  # noqa: BLE001 - option names drift across jax
            pass
    return ok


class ProgramRegistry:
    """Hashable-key -> compiled/jitted program, built once per process.

    ``name`` labels the instance in snapshots (the global instance is
    ``"global"``; the serving layer names its per-service instances
    ``"serve"``).  For AOT consumers (serve) a build IS an XLA compile;
    for ``jax.jit`` consumers (ensemble/MC/export) a build constructs
    the traced callable once and XLA compiles lazily per input shape —
    either way, build count 1 per key is the no-duplicate-work contract
    the gates pin.
    """

    #: default artifact cap — far above any real process's distinct
    #: geometry count, small enough that a parameter scan constructing
    #: thousands of distinct studies cannot grow memory without bound
    #: (per-instance caches used to die with their instances; a
    #: process-global store needs an explicit bound)
    DEFAULT_MAX_PROGRAMS = 256

    def __init__(self, name="global", compile_cache_dir=None, timers=None,
                 max_programs=None):
        from collections import OrderedDict

        self.name = str(name)
        self._lock = threading.Lock()
        self._programs = OrderedDict()  # key -> artifact (LRU order)
        self._max_programs = int(max_programs
                                 if max_programs is not None
                                 else self.DEFAULT_MAX_PROGRAMS)
        self._builds = {}         # key -> build count (1 unless evicted)
        self._hits = {}           # key -> get_or_build calls served cached
        self._build_seconds = 0.0
        self._evictions = 0
        self._timers = timers
        self.cache_enabled = (
            enable_compilation_cache(compile_cache_dir)
            if compile_cache_dir else False)

    # -- resolution --------------------------------------------------------

    def get_or_build(self, key, builder):
        """The program for ``key``, building it with ``builder()`` on
        first use.  Concurrent builders of the same key may both run;
        exactly one artifact is kept (both are valid — the counts record
        what actually happened, which is what the single-build gates
        check after warmup).

        The store is an LRU bounded at ``max_programs`` artifacts:
        consumers keep their own references, so eviction only costs a
        rebuild if a long-gone geometry returns (and bumps that key's
        build count past 1 — the single-build gates run at warmup
        scales, far under the cap)."""
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self._programs.move_to_end(key)
                self._hits[key] = self._hits.get(key, 0) + 1
                return prog
        t0 = time.perf_counter()
        built = builder()
        dt = time.perf_counter() - t0
        with self._lock:
            self._builds[key] = self._builds.get(key, 0) + 1
            self._build_seconds += dt
            prog = self._programs.setdefault(key, built)
            self._programs.move_to_end(key)
            while len(self._programs) > self._max_programs:
                self._programs.popitem(last=False)
                self._evictions += 1
            timers = self._timers
        if timers is not None:
            timers.add("compile", dt)
            timers.count("program_builds")
        return prog

    def peek(self, key):
        """The cached program or None — never builds."""
        with self._lock:
            return self._programs.get(key)

    # -- telemetry ---------------------------------------------------------

    def attach_timers(self, timers):
        """Route build telemetry into ``timers`` (a
        :class:`~psrsigsim_tpu.runtime.telemetry.StageTimers`): each
        subsequent build adds one ``"compile"`` stage sample and bumps
        the ``program_builds`` counter.  Last attach wins; pass None to
        detach."""
        with self._lock:
            self._timers = timers

    def build_counts(self):
        with self._lock:
            return dict(self._builds)

    def hit_counts(self):
        with self._lock:
            return dict(self._hits)

    def assert_single_build(self, family=None):
        """The shared-registry no-duplicate-work guard: every key (or
        every key of one ``family`` prefix) was built exactly once."""
        bad = {k: c for k, c in self.build_counts().items()
               if c != 1 and (family is None or k[0] == family)}
        if bad:
            raise AssertionError(
                f"registry {self.name!r}: programs built more than once: "
                f"{bad}")

    def snapshot(self):
        """JSON-ready summary (family-aggregated: raw keys hold live
        config objects that do not belong in a manifest)."""
        with self._lock:
            fams = {}
            for k, c in self._builds.items():
                fam = k[0] if isinstance(k, tuple) and k else str(k)
                fams[str(fam)] = fams.get(str(fam), 0) + c
            hits = {}
            for k, c in self._hits.items():
                fam = k[0] if isinstance(k, tuple) and k else str(k)
                hits[str(fam)] = hits.get(str(fam), 0) + c
            return {
                "registry": self.name,
                "programs": len(self._programs),
                "builds_total": int(sum(self._builds.values())),
                "build_seconds": round(self._build_seconds, 6),
                "evictions": self._evictions,
                "builds_by_family": dict(sorted(fams.items())),
                "hits_by_family": dict(sorted(hits.items())),
            }


# the process-wide instance: ensemble / MC / export program families all
# resolve here, so constructing a second FoldEnsemble (or study, or
# exporter) over an already-seen geometry is a registry hit, not a
# re-trace.  Memory is bounded by the LRU cap (DEFAULT_MAX_PROGRAMS) —
# a parameter scan over thousands of distinct geometries recycles the
# oldest artifacts instead of growing forever.
_GLOBAL = ProgramRegistry("global")


def global_registry():
    """The process-wide shared :class:`ProgramRegistry`."""
    return _GLOBAL
