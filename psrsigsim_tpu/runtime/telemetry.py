"""Per-stage telemetry for the streaming export pipeline.

The bulk-export path is a four-stage pipeline — **dispatch** (host-side
program launch + input staging), **fetch** (device->host transfer, on a
dedicated thread), **encode** (host byte assembly: packer slices, SUBINT
record refills, shared-memory copies) and **write** (writev/rename, or
the parent's wait on the writer pool) — with bounded queues between the
stages.  When throughput disappoints, the question is always "which
stage is the bottleneck on THIS host?", and the answer used to require
reverse-engineering bench JSON (BENCH_r03-r05 each did it by hand).

:class:`StageTimers` is the shared accumulator every stage reports into:
monotonic per-stage busy time, call counts, fetched bytes, and bounded-
queue depth samples.  The exporter folds a snapshot into the export
manifest (``pipeline`` key) and ``bench.py``'s ``export_e2e`` section
surfaces it, so every run names its own bottleneck.

Thread-safety: ``add``/``depth`` are called from the fetch thread and
the main thread concurrently; all mutation is under one lock.  The
object is deliberately NOT picklable state for spawn workers — worker-
side costs surface as the parent's ``write`` wait, which is the number
the pipeline actually pays.
"""

from __future__ import annotations

import threading
import time

__all__ = ["StageTimers", "STAGES"]

STAGES = ("dispatch", "fetch", "encode", "write")


class StageTimers:
    """Monotonic per-stage busy-time accumulator for one export run.

    ``extra_stages`` declares additional stage names beyond the export
    pipeline's canonical four — the Monte-Carlo study engine reports its
    host-side accumulator merge as ``"reduce"`` — so a consumer with a
    different pipeline shape reuses the same accumulator, snapshot
    format, and bottleneck logic instead of growing a parallel one.
    """

    def __init__(self, extra_stages=()):
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._stages = tuple(STAGES) + tuple(
            s for s in extra_stages if s not in STAGES)
        self._seconds = {k: 0.0 for k in self._stages}
        self._calls = {k: 0 for k in self._stages}
        self._bytes_fetched = 0
        self._depths = {}  # queue name -> [sum, samples, max]

    def add(self, stage, seconds, nbytes=0):
        """Accumulate ``seconds`` of busy time against ``stage`` (one of
        :data:`STAGES` or a declared extra stage; an undeclared name is
        registered on first use so a shared timer object never throws
        from a reporting thread); ``nbytes`` counts device->host payload
        bytes (fetch stage only, by convention)."""
        with self._lock:
            if stage not in self._seconds:
                self._stages = self._stages + (stage,)
                self._seconds[stage] = 0.0
                self._calls[stage] = 0
            self._seconds[stage] += float(seconds)
            self._calls[stage] += 1
            self._bytes_fetched += int(nbytes)

    def depth(self, name, value):
        """Record one bounded-queue depth sample (e.g. the fetched-chunk
        queue right before the consumer pops it: 0 means the consumer
        starved, full means the consumer is the bottleneck)."""
        with self._lock:
            rec = self._depths.setdefault(name, [0, 0, 0])
            rec[0] += int(value)
            rec[1] += 1
            rec[2] = max(rec[2], int(value))

    def snapshot(self):
        """One JSON-ready dict: per-stage seconds/counts, fetched bytes,
        queue-depth stats, wall time, and the named bottleneck stage (the
        stage with the most accumulated busy time — in an ideally
        overlapped pipeline its time approaches the wall time and every
        other stage hides under it)."""
        with self._lock:
            out = {}
            for k in self._stages:
                out[f"{k}_s"] = round(self._seconds[k], 6)
                out[f"{k}_calls"] = self._calls[k]
            out["bytes_fetched"] = self._bytes_fetched
            out["wall_s"] = round(time.perf_counter() - self._t0, 6)
            for name, (tot, n, mx) in sorted(self._depths.items()):
                out[f"{name}_depth_max"] = mx
                out[f"{name}_depth_mean"] = round(tot / max(n, 1), 3)
            out["bottleneck"] = max(self._stages,
                                    key=lambda k: self._seconds[k])
            return out
