"""Per-stage telemetry for the streaming export pipeline.

The bulk-export path is a four-stage pipeline — **dispatch** (host-side
program launch + input staging), **fetch** (device->host transfer, on a
dedicated thread), **encode** (host byte assembly: packer slices, SUBINT
record refills, shared-memory copies) and **write** (writev/rename, or
the parent's wait on the writer pool) — with bounded queues between the
stages.  When throughput disappoints, the question is always "which
stage is the bottleneck on THIS host?", and the answer used to require
reverse-engineering bench JSON (BENCH_r03-r05 each did it by hand).

:class:`StageTimers` is the shared accumulator every stage reports into:
monotonic per-stage busy time, call counts, fetched bytes, and bounded-
queue depth samples.  The exporter folds a snapshot into the export
manifest (``pipeline`` key) and ``bench.py``'s ``export_e2e`` section
surfaces it, so every run names its own bottleneck.

Thread-safety: ``add``/``depth`` are called from the fetch thread and
the main thread concurrently; all mutation is under one lock.  The
object is deliberately NOT picklable state for spawn workers — worker-
side costs surface as the parent's ``write`` wait, which is the number
the pipeline actually pays.
"""

from __future__ import annotations

import math
import threading
import time

__all__ = ["StageTimers", "STAGES", "LATENCY_LOG10_LO", "LATENCY_LOG10_HI",
           "LATENCY_NBINS", "latency_bin_index", "latency_bin_edges"]

STAGES = ("dispatch", "fetch", "encode", "write")

# Bounded per-stage latency histogram: fixed equal bins over
# log10(seconds) in [LATENCY_LOG10_LO, LATENCY_LOG10_HI), out-of-range
# samples clamped into the edge bins — the host-side mirror of
# ``ops/stats.fixed_histogram`` semantics (equal bins, clamp-not-drop),
# applied to log-latency so microsecond encode calls and multi-second
# device dispatches share one fixed-size table.  10 bins per decade from
# 1 us to 100 s: memory is ``nbins`` ints per stage, forever bounded.
LATENCY_LOG10_LO = -6.0
LATENCY_LOG10_HI = 2.0
LATENCY_NBINS = 80


def latency_bin_index(seconds):
    """The histogram bin a latency sample lands in (clamped into the edge
    bins exactly like ``fixed_histogram`` clamps its tails)."""
    s = max(float(seconds), 1e-30)
    span = LATENCY_LOG10_HI - LATENCY_LOG10_LO
    idx = int(math.floor(
        (math.log10(s) - LATENCY_LOG10_LO) / span * LATENCY_NBINS))
    return min(max(idx, 0), LATENCY_NBINS - 1)


def latency_bin_edges():
    """Bin UPPER edges in SECONDS (len ``LATENCY_NBINS``): bin ``i``
    spans ``[edges[i-1], edges[i])`` (lower edge of bin 0 is
    ``10**LATENCY_LOG10_LO``), with out-of-range samples clamped into
    bins 0 and ``LATENCY_NBINS - 1``."""
    span = LATENCY_LOG10_HI - LATENCY_LOG10_LO
    return [10.0 ** (LATENCY_LOG10_LO + (i + 1) * span / LATENCY_NBINS)
            for i in range(LATENCY_NBINS)]


def _hist_percentile(counts, q):
    """Percentile estimate from the fixed-bin histogram: the UPPER edge
    (in seconds) of the bin where the cumulative count crosses ``q`` —
    conservative (never under-reports) and exact to one bin width
    (~26% in time, 10 bins/decade)."""
    total = sum(counts)
    if total == 0:
        return 0.0
    edges = latency_bin_edges()
    target = q * total
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= target:
            return edges[i]
    return edges[-1]


class StageTimers:
    """Monotonic per-stage busy-time accumulator for one export run.

    ``extra_stages`` declares additional stage names beyond the export
    pipeline's canonical four — the Monte-Carlo study engine reports its
    host-side accumulator merge as ``"reduce"`` — so a consumer with a
    different pipeline shape reuses the same accumulator, snapshot
    format, and bottleneck logic instead of growing a parallel one.

    ``latency_stages`` names stages that record END-TO-END latency
    rather than exclusive busy time (the serving engine's ``"request"``
    stage spans queue wait + batch window + compute, once per request):
    they get the same histograms/percentiles but are excluded from the
    ``bottleneck`` pick, which compares exclusive busy totals — an e2e
    stage double-counts every other stage and would always win.
    """

    def __init__(self, extra_stages=(), latency_stages=()):
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._stages = tuple(STAGES) + tuple(
            s for s in extra_stages if s not in STAGES)
        self._latency_stages = frozenset(latency_stages)
        self._seconds = {k: 0.0 for k in self._stages}
        self._calls = {k: 0 for k in self._stages}
        self._hist = {k: [0] * LATENCY_NBINS for k in self._stages}
        self._bytes_fetched = 0
        self._stage_bytes = {}  # stage -> payload bytes reported to it
        self._depths = {}  # queue name -> [sum, samples, max]
        self._counters = {}  # name -> int (program builds, cache events...)
        self._gauges = {}  # name -> last-set value (degraded flags, levels)
        self._live_bytes = 0  # dispatched-but-unfetched device bytes

    def add(self, stage, seconds, nbytes=0):
        """Accumulate ``seconds`` of busy time against ``stage`` (one of
        :data:`STAGES` or a declared extra stage; an undeclared name is
        registered on first use so a shared timer object never throws
        from a reporting thread); ``nbytes`` counts the stage's payload
        bytes — device->host transfers for ``fetch``, committed record
        bytes for the dataset factory's ``write``, ... — accumulated
        per stage (``<stage>_bytes`` in snapshots; the legacy
        ``bytes_fetched`` total keeps summing every report, which
        matches its historical value because only ``fetch`` reported
        bytes before per-stage accounting existed).  Each call also
        lands one sample in the stage's bounded latency histogram, from
        which :meth:`snapshot` reports p50/p95/p99."""
        with self._lock:
            if stage not in self._seconds:
                self._stages = self._stages + (stage,)
                self._seconds[stage] = 0.0
                self._calls[stage] = 0
                self._hist[stage] = [0] * LATENCY_NBINS
            self._seconds[stage] += float(seconds)
            self._calls[stage] += 1
            self._hist[stage][latency_bin_index(seconds)] += 1
            if nbytes:
                self._stage_bytes[stage] = (
                    self._stage_bytes.get(stage, 0) + int(nbytes))
                if stage == "fetch":
                    self._bytes_fetched += int(nbytes)

    def histogram(self, stage):
        """A copy of one stage's latency-histogram counts (len
        :data:`LATENCY_NBINS`; bin semantics in :func:`latency_bin_index`)."""
        with self._lock:
            return list(self._hist.get(stage, [0] * LATENCY_NBINS))

    def percentile(self, stage, q):
        """Latency percentile ``q`` (0..1) for ``stage``, estimated from
        the bounded histogram (conservative: the crossing bin's upper
        edge; 0.0 when the stage never reported)."""
        with self._lock:
            return _hist_percentile(self._hist.get(stage, ()), q)

    def count(self, name, n=1):
        """Bump a named event counter (e.g. ``program_builds`` from the
        shared program registry): counters ride every snapshot as
        ``<name>_count``, so manifests and bench JSON record how many
        compiles/builds a run actually paid — the compile-count
        telemetry of the shared registry (ROADMAP item 5)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(n)

    def counter(self, name):
        with self._lock:
            return self._counters.get(name, 0)

    def track_live(self, tree):
        """Add a just-dispatched device pytree's bytes to the
        ``live_buffer_bytes`` gauge — the donation satellite's measure
        of dispatched-but-unfetched HBM, shared by every chunked
        producer (ensemble/MC/dataset) so the accounting lives in ONE
        place; :meth:`untrack_live` subtracts the same tree on fetch."""
        self._bump_live(tree, +1)

    def untrack_live(self, tree):
        """Subtract a fetched device pytree's bytes from the
        ``live_buffer_bytes`` gauge (clamped at zero: a producer that
        fetches a tree it never tracked must not drive the gauge
        negative)."""
        self._bump_live(tree, -1)

    def _bump_live(self, tree, sign):
        import jax

        n = sum(int(a.nbytes) for a in jax.tree_util.tree_leaves(tree))
        with self._lock:
            self._live_bytes = max(0, self._live_bytes + sign * n)
            self._gauges["live_buffer_bytes"] = self._live_bytes

    def gauge(self, name, value):
        """Set a named point-in-time gauge (e.g. ``cache_degraded`` while
        the serving cache tier is in ENOSPC pass-through, or a fleet's
        ``active_replicas``): unlike counters these carry the CURRENT
        value, not an accumulation, and ride snapshots as
        ``<name>_gauge`` so /metrics and bench JSON see state, not just
        history."""
        with self._lock:
            self._gauges[name] = value

    def set_gauges(self, values):
        """Set several gauges under ONE lock acquisition — the serving
        front end's periodic tick (open connections, event-loop lag,
        pending write bytes) exports its gauges in a batch so a
        hot event loop pays one lock round-trip per tick, not one per
        gauge."""
        with self._lock:
            self._gauges.update(values)

    def gauge_value(self, name, default=None):
        with self._lock:
            return self._gauges.get(name, default)

    def depth(self, name, value):
        """Record one bounded-queue depth sample (e.g. the fetched-chunk
        queue right before the consumer pops it: 0 means the consumer
        starved, full means the consumer is the bottleneck)."""
        with self._lock:
            rec = self._depths.setdefault(name, [0, 0, 0])
            rec[0] += int(value)
            rec[1] += 1
            rec[2] = max(rec[2], int(value))

    def snapshot(self):
        """One JSON-ready dict: per-stage seconds/counts, fetched bytes,
        queue-depth stats, wall time, and the named bottleneck stage (the
        stage with the most accumulated busy time — in an ideally
        overlapped pipeline its time approaches the wall time and every
        other stage hides under it)."""
        with self._lock:
            out = {}
            for k in self._stages:
                out[f"{k}_s"] = round(self._seconds[k], 6)
                out[f"{k}_calls"] = self._calls[k]
                if self._calls[k]:
                    # per-call latency percentiles from the bounded
                    # histogram (satellite of the serving PR: /metrics
                    # and bench JSON report p50/p95/p99 per stage)
                    for tag, q in (("p50", 0.50), ("p95", 0.95),
                                   ("p99", 0.99)):
                        out[f"{k}_{tag}_s"] = round(
                            _hist_percentile(self._hist[k], q), 6)
            out["bytes_fetched"] = self._bytes_fetched
            for name, n in sorted(self._stage_bytes.items()):
                out[f"{name}_bytes"] = n
            out["wall_s"] = round(time.perf_counter() - self._t0, 6)
            for name, n in sorted(self._counters.items()):
                out[f"{name}_count"] = n
            for name, v in sorted(self._gauges.items()):
                out[f"{name}_gauge"] = v
            for name, (tot, n, mx) in sorted(self._depths.items()):
                out[f"{name}_depth_max"] = mx
                out[f"{name}_depth_mean"] = round(tot / max(n, 1), 3)
            busy = [k for k in self._stages
                    if k not in self._latency_stages] or list(self._stages)
            out["bottleneck"] = max(busy, key=lambda k: self._seconds[k])
            return out
