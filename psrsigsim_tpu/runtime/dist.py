"""Multi-host pod runtime: coordinator bootstrap, global meshes, and the
host-side primitives that let one logical program span every chip across
every host in a pod slice.

Every compiled program in the repo shards over a ``jax.sharding.Mesh``;
until this module that mesh was always ONE process's local devices —
PR 7's :class:`~psrsigsim_tpu.serve.ReplicaFleet` scales processes, not
meshes (ROADMAP item 1).  SNIPPETS.md [1] names the missing mechanism:
on multi-process platforms "pjit can be used to run computations across
all available devices across processes."  This module is that story,
end to end:

* :func:`init_pod` — coordinator bootstrap.  Reads the ``PSS_POD_*``
  environment (or explicit arguments), wires CPU collectives (gloo) when
  the platform needs them, and calls ``jax.distributed.initialize`` —
  after which ``jax.devices()`` returns the GLOBAL device list and the
  existing :func:`~psrsigsim_tpu.parallel.make_mesh` builds a pod-wide
  mesh with no further changes.  Unconfigured, it is a no-op: every
  consumer takes exactly the pre-pod code path (the single-process
  fallback is byte-identical by construction).
* :func:`put_sharded` / :func:`device_get` — the two operations that
  differ under a pod.  ``jax.device_put`` refuses typed-key arrays on
  non-addressable shardings, so ``put_sharded`` assembles the global
  array from per-device slices of the (replicated) host value — every
  process stages the SAME host bytes, each placing only its addressable
  shards.  ``device_get`` replicates a global array in-graph (a cached
  all-gather identity program per (sharding, shape, dtype)) and reads
  the local copy, so every process returns the FULL host array and the
  downstream host logic (journals, writers, result merges) runs the
  same control flow everywhere — which is what keeps a pod in lockstep
  without a consensus protocol.
* :class:`PodChannel` — a loopback-free TCP side channel (leader binds,
  followers connect) carrying control traffic the SPMD program cannot:
  the serving layer's batch broadcast, barriers, and the peer-death
  watchdog.  A follower SIGKILL'd mid-run must surface as a supervisor
  restart of the whole program group, NOT a hang in a collective — the
  watchdog turns peer-socket EOF into an immediate loud exit
  (:data:`POD_PEER_EXIT`), which the process supervisor sees like any
  other death.
* :func:`pod_key` / :func:`compile_cache_path` — the registry/cache
  audit hooks: program-registry keys fold in the (process-id-
  independent) pod topology via
  :func:`~psrsigsim_tpu.runtime.programs.trace_env_key`, and the
  persistent compilation cache lands in a per-host-count subdirectory,
  so a cached single-host program can never be served to a pod mesh.

Reproducibility: all randomness is keyed by (seed, GLOBAL index), so a
pod mesh with the same global device count computes bit-identical
results at any host count {1, 2, 4, ...} — the pod analogue of the
chunk-size invariance, pinned by tests/pod_runner.py the same way.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import socket
import struct
import sys
import threading
import time

__all__ = ["init_pod", "pod_info", "is_pod", "is_leader", "pod_key",
           "put_sharded", "device_get", "local_rows", "pod_process_mesh",
           "compile_cache_path", "PodChannel", "PodPeerLost", "PodInfo",
           "pod_channel", "pod_barrier", "shutdown_pod", "POD_PEER_EXIT",
           "free_ports"]

#: exit code of a process that lost a pod peer mid-run: deterministic
#: and loud, so the supervising layer restarts the whole program group
#: instead of diagnosing a wedged collective
POD_PEER_EXIT = 73

_FRAME = struct.Struct("!I")
_BYE = b"\x00POD-BYE\x00"


def free_ports(n=1):
    """Allocate ``n`` distinct kernel-assigned loopback ports (bind to
    port 0, read the name, close).  Every pod launcher — the fleet's
    group spawner, the smoke gates, the cluster test harnesses — needs
    coordinator + channel ports for processes it is ABOUT to spawn;
    this is the one shared implementation.  All ``n`` sockets are held
    open until the last is bound so the returned ports are distinct."""
    socks = [socket.socket() for _ in range(n)]
    try:
        for sk in socks:
            sk.bind(("127.0.0.1", 0))
        return [sk.getsockname()[1] for sk in socks]
    finally:
        for sk in socks:
            sk.close()


class PodPeerLost(RuntimeError):
    """A pod peer died (socket EOF without the clean-shutdown frame)."""


class PodInfo:
    """This process's pod coordinates (immutable after :func:`init_pod`)."""

    def __init__(self, process_id=0, num_processes=1, coordinator=None,
                 channel_port=None, initialized=False):
        self.process_id = int(process_id)
        self.num_processes = int(num_processes)
        self.coordinator = coordinator
        self.channel_port = channel_port
        self.initialized = bool(initialized)

    @property
    def is_pod(self):
        return self.initialized and self.num_processes > 1

    @property
    def is_leader(self):
        return self.process_id == 0

    def describe(self):
        return {"process_id": self.process_id,
                "num_processes": self.num_processes,
                "is_pod": self.is_pod}

    def __repr__(self):
        return (f"PodInfo(process_id={self.process_id}, "
                f"num_processes={self.num_processes}, "
                f"initialized={self.initialized})")


_SOLO = PodInfo()
_pod = _SOLO
_channel = None
_lock = threading.Lock()


def _env_int(name):
    v = os.environ.get(name, "").strip()
    return int(v) if v else None


def _jax_backend_started():
    """Best-effort: has any XLA backend already initialized?  The pod
    MUST bootstrap before the first backend touch (the CPU collectives
    option and the distributed client bind at backend creation)."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:  # pragma: no cover - internal layout drift
        return False


# Pod membership IS process-global state: one process is one pod
# member, jax.distributed itself is a process-global singleton, and
# every consumer (registry keys, cache paths, leader gates) must see
# ONE consistent topology.  Rebinding is confined to the explicit
# lifecycle entries (init/shutdown/test-reset), each PSR105-suppressed.
def init_pod(coordinator=None, num_processes=None,  # psrlint: disable=PSR105
             process_id=None, channel_port=None, channel=True,
             timeout_s=60.0):
    """Join (or skip) the pod.  Idempotent.

    Args default from the environment: ``PSS_POD_COORDINATOR``
    (``host:port`` of process 0's coordinator service),
    ``PSS_POD_NUM_PROCESSES``, ``PSS_POD_PROCESS_ID``,
    ``PSS_POD_CHANNEL_PORT`` (default: coordinator port + 1; the host
    side channel binds on the leader).  With no coordinator configured
    (or ``num_processes`` <= 1) this registers the single-process
    fallback and changes NOTHING — every dist helper reduces to the
    plain jax call, and compiled programs are exactly the pre-pod ones.

    Must run before the first jax computation: the CPU-collectives
    wiring and the distributed client attach at backend creation.
    """
    global _pod, _channel
    with _lock:
        if _pod.initialized:
            return _pod
        coordinator = coordinator or os.environ.get("PSS_POD_COORDINATOR")
        num_processes = (num_processes if num_processes is not None
                         else _env_int("PSS_POD_NUM_PROCESSES"))
        process_id = (process_id if process_id is not None
                      else _env_int("PSS_POD_PROCESS_ID"))
        if not coordinator or not num_processes or num_processes <= 1:
            _pod = PodInfo(initialized=True)
            return _pod
        if process_id is None:
            raise ValueError(
                "pod bootstrap needs a process id: set PSS_POD_PROCESS_ID "
                "(or pass process_id=)")
        if _jax_backend_started():
            raise RuntimeError(
                "init_pod() must run before the first jax computation "
                "(an XLA backend is already initialized); call it at "
                "process start, right after importing jax")
        import jax

        # CPU multi-process execution needs an explicit collectives
        # implementation (the default 'none' refuses cross-process
        # programs outright); accelerator backends bring their own.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # pragma: no cover - option drift across jax
            pass
        jax.distributed.initialize(coordinator_address=str(coordinator),
                                   num_processes=int(num_processes),
                                   process_id=int(process_id))
        info = PodInfo(process_id=process_id, num_processes=num_processes,
                       coordinator=str(coordinator), initialized=True)
        if channel:
            port = (channel_port if channel_port is not None
                    else _env_int("PSS_POD_CHANNEL_PORT"))
            if port is None:
                port = int(str(coordinator).rsplit(":", 1)[1]) + 1
            info.channel_port = int(port)
            _channel = PodChannel(info, int(port), timeout_s=timeout_s)
        _pod = info
        return _pod


def pod_info():
    """This process's :class:`PodInfo` (the solo default before
    :func:`init_pod` runs)."""
    return _pod


def pod_channel():
    """The bootstrap :class:`PodChannel` (None when solo / disabled)."""
    return _channel


def is_pod():
    return _pod.is_pod


def is_leader():
    """True when this process owns the pod's host-side effects (journal
    writes, manifests, HTTP endpoints).  Solo processes lead trivially."""
    return _pod.is_leader


def pod_key():
    """The registry-key topology fingerprint: process-id-INDEPENDENT (a
    pod's processes must resolve identical keys) but host-count-aware (a
    single-host program must never be served to a pod mesh).  Folded
    into every device-program registry key via
    :func:`~psrsigsim_tpu.runtime.programs.trace_env_key`."""
    if not _pod.is_pod:
        return ("solo",)
    return ("pod", _pod.num_processes)


def compile_cache_path(base):
    """The persistent-compilation-cache directory for THIS topology: a
    ``hosts<N>`` subdirectory under a pod, ``base`` itself when solo —
    the cache-path half of the key audit (jax's own cache key covers
    device assignment, but a shared artifact store must stay legible:
    one topology, one directory, and a joining host warms from exactly
    its pod's artifacts)."""
    if not _pod.is_pod:
        return str(base)
    return os.path.join(str(base), f"hosts{_pod.num_processes}")


def pod_barrier(tag="sync", timeout_s=120.0):
    """Channel-based host barrier (no-op when solo / channel disabled)."""
    if _channel is not None:
        _channel.barrier(tag, timeout_s=timeout_s)


def shutdown_pod():  # psrlint: disable=PSR105 (the pod lifecycle; see init_pod)
    """Clean pod teardown: send the clean-shutdown frame on the watch
    socket (so peers don't mistake this exit for a death) and close the
    channel.  Safe to call when solo (no-op)."""
    global _channel
    ch = _channel
    _channel = None
    if ch is not None:
        ch.close()


# ---------------------------------------------------------------------------
# global-array staging and fetch
# ---------------------------------------------------------------------------


def put_sharded(x, sharding):
    """Place a (replicated) host value onto ``sharding`` — the pod-safe
    ``jax.device_put``.

    Solo (or addressable shardings): exactly ``jax.device_put(x,
    sharding)`` — the pre-pod behavior, bit for bit.  Under a pod every
    process calls this with the SAME host value; each slices out and
    places only its addressable shards and assembles the global array
    (``make_array_from_single_device_arrays``), which is the only
    staging path that also carries typed PRNG-key arrays."""
    import jax

    if (not _pod.is_pod) or getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(x, sharding)
    import numpy as np

    shape = x.shape if hasattr(x, "shape") else np.shape(x)
    idx_map = sharding.addressable_devices_indices_map(tuple(shape))
    arrs = [jax.device_put(x[idx], d) for d, idx in idx_map.items()]
    return jax.make_array_from_single_device_arrays(
        tuple(shape), sharding, arrs)


def _replicate(x):
    """A fully-replicated copy of a global array: one cached identity
    program per (sharding, shape, dtype) whose output sharding drops
    every partition — XLA lowers it to the all-gather this fetch IS.
    Resolved through the shared program registry (family
    ``pod_replicate``) so builds are counted like any other program."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from .programs import global_registry

    sharding = x.sharding
    out_sh = NamedSharding(sharding.mesh, PartitionSpec())
    prog = global_registry().get_or_build(
        ("pod_replicate", sharding, tuple(x.shape), str(x.dtype)),
        lambda: jax.jit(lambda a: a, out_shardings=out_sh))
    return prog(x)


def _channel_fetch(x, ch):
    """Exchange one global array's shards over the pod channel: every
    process fetches its LOCAL shards (no collective), followers ship
    theirs to the leader, and the leader returns each follower only the
    COMPLEMENT of its own shards (every process already holds 1/N of
    the bytes locally — re-sending them would pay ~2x the necessary
    leader egress per chunk) — all on the strictly-FIFO ctl stream.

    This is the DEFAULT pod fetch because it is deterministic by
    construction: in-graph all-gathers from overlapping programs share
    the backend's collective streams, and on the CPU/gloo stack an
    interleaving across the dispatch-ahead window can corrupt or wedge
    them.  The channel path involves no collectives at all; the
    in-graph path stays available for real accelerator pods
    (``PSS_POD_FETCH=collective`` — ICI all-gathers dwarf loopback
    TCP).

    Every frame carries the per-process monotonic fetch sequence number
    and the leaf shape/dtype: lockstep is an INVARIANT, so a divergence
    (one side skipped a chunk the other computed) must surface as this
    loud mismatch — never as shape-compatible shards of the wrong chunk
    silently assembled into the result."""
    import numpy as np

    seq = ch.next_fetch_seq()
    meta = (tuple(x.shape), str(x.dtype))
    local = [(s.index, np.asarray(s.data)) for s in x.addressable_shards]
    if _pod.is_leader:
        out = np.zeros(x.shape, x.dtype)
        for idx, block in local:
            out[idx] = block
        peer = {}
        for pid, payload in ch.gather().items():
            tag, got_seq, got_meta, shards = payload
            if tag != "pod-fetch" or got_seq != seq or got_meta != meta:
                raise RuntimeError(
                    f"pod fetch #{seq} {meta}: peer {pid} sent "
                    f"{(tag, got_seq, got_meta)!r} — program groups out "
                    "of lockstep")
            for idx, block in shards:
                out[idx] = block
            peer[pid] = shards
        for pid in peer:
            parts = list(local)
            for other, shards in peer.items():
                if other != pid:
                    parts.extend(shards)
            ch.send_to(pid, ("pod-fetch-part", seq, meta, parts))
        return out
    ch.send_to_leader(("pod-fetch", seq, meta, local))
    tag, got_seq, got_meta, parts = ch.recv()
    if tag != "pod-fetch-part" or got_seq != seq or got_meta != meta:
        raise RuntimeError(
            f"pod fetch #{seq} {meta}: leader sent "
            f"{(tag, got_seq, got_meta)!r} — program groups out of "
            "lockstep")
    out = np.zeros(x.shape, x.dtype)
    for idx, block in local:
        out[idx] = block
    for idx, block in parts:
        out[idx] = block
    return out


def device_get(tree):
    """Fetch a pytree of device arrays to host — the pod-safe
    ``jax.device_get``.

    Solo: exactly ``jax.device_get(tree)``.  Under a pod, leaves whose
    shards span other hosts are exchanged over the pod channel
    (:func:`_channel_fetch`, the deterministic default) or replicated
    in-graph (``PSS_POD_FETCH=collective`` — :func:`_replicate`, for
    accelerator pods with native collective fabrics) — either way EVERY
    process returns the full host value, so downstream host logic
    (quarantine decisions, journal commits, result merges) takes
    identical branches on every host.  That lockstep is the pod's
    consistency model: the fetch is also the rendezvous.

    Single-owner rule: one thread per process drives pod fetches at a
    time (the chunk pipelines' fetch thread, the serve batcher, or the
    study loop) — the channel stream is FIFO, not multiplexed."""
    import jax

    if not _pod.is_pod:
        return jax.device_get(tree)
    import numpy as np

    mode = os.environ.get("PSS_POD_FETCH", "channel").strip().lower()
    ch = _channel if mode != "collective" else None
    if mode not in ("channel", "collective"):
        raise ValueError(f"PSS_POD_FETCH={mode!r}: use channel or "
                         "collective")
    if mode == "channel" and ch is None:
        raise RuntimeError("pod fetch needs the pod channel (init_pod "
                           "with channel=True), or PSS_POD_FETCH="
                           "collective")

    def _leaf(x):
        if not isinstance(x, jax.Array) or x.is_fully_addressable:
            return jax.device_get(x)
        if ch is not None:
            return _channel_fetch(x, ch)
        full = _replicate(x)
        return np.asarray(full.addressable_shards[0].data)

    return jax.tree_util.tree_map(_leaf, tree)


def local_rows(arr):
    """This process's rows of a leading-axis-sharded global array:
    ``(global_row_indices, host_block)`` — the per-host view identity
    tests hash (no collective, no cross-host traffic)."""
    import numpy as np

    shards = sorted(arr.addressable_shards,
                    key=lambda s: s.index[0].start or 0)
    idx = np.concatenate([
        np.arange(s.index[0].start or 0,
                  s.index[0].stop if s.index[0].stop is not None
                  else arr.shape[0])
        for s in shards])
    block = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
    return idx, block


def pod_process_mesh():
    """A 2-D ``(obs, chan)`` mesh with ONE device per pod process —
    the serving layer's pod mesh (request batches are small; what a pod
    replica spans is HOSTS, with obs rows split one slab per host).
    Solo: the first local device only."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from ..parallel.mesh import CHAN_AXIS, OBS_AXIS

    seen = set()
    devs = []
    for d in jax.devices():
        if d.process_index not in seen:
            seen.add(d.process_index)
            devs.append(d)
    return Mesh(np.array(devs).reshape(len(devs), 1), (OBS_AXIS, CHAN_AXIS))


# ---------------------------------------------------------------------------
# the host-side channel
# ---------------------------------------------------------------------------


def _send_frame(sock, payload):
    sock.sendall(_FRAME.pack(len(payload)) + payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise PodPeerLost("pod peer closed the channel mid-frame")
        buf += chunk
    return buf


def _recv_frame(sock):
    (n,) = _FRAME.unpack(_recv_exact(sock, _FRAME.size))
    return _recv_exact(sock, n)


#: the hello handshake is a FIXED-SIZE, HMAC-authenticated frame — the
#: one part of the channel protocol that reads bytes from a socket that
#: has not proven it is a pod peer, so it must never touch pickle (a
#: crafted pickle IS remote code execution) and must reject forgeries
#: before a stray/hostile connection can claim a follower slot
_HELLO = struct.Struct("!cI")   # kind byte (c=ctl, w=watch) + process id
_HELLO_MAC = hashlib.sha256().digest_size


def _channel_token(info):
    """The shared channel secret: ``PSS_POD_TOKEN`` when the operator
    sets one (REQUIRED on any non-loopback deployment), else derived
    from the pod coordinates so same-machine clusters authenticate
    against casual strays without configuration."""
    tok = os.environ.get("PSS_POD_TOKEN")
    if tok:
        return tok.encode()
    return hashlib.sha256(
        f"pss-pod:{info.coordinator}:{info.num_processes}".encode()
    ).digest()


def _hello_frame(kind, pid, token):
    head = _HELLO.pack(b"c" if kind == "ctl" else b"w", pid)
    mac = hmac.new(token, b"pss-pod-hello" + head, hashlib.sha256).digest()
    return head + mac


class PodChannel:
    """Leader-rooted control channel + peer-death watchdog.

    Two sockets per follower: a ``ctl`` stream carrying protocol frames
    (length-prefixed pickles — safe because every peer first proved
    itself with the HMAC hello below; nothing pickled is ever read from
    an unauthenticated socket) and a ``watch`` stream that carries
    NOTHING except the clean-
    shutdown frame: a watchdog thread blocks on it, and EOF without
    :data:`_BYE` means the peer died — the default reaction is an
    immediate ``os._exit(POD_PEER_EXIT)``, turning a wedged-collective
    hang into a process death the supervising layer already knows how
    to restart.  Pass ``on_peer_lost`` to override (tests).
    """

    def __init__(self, info, port, timeout_s=60.0, on_peer_lost=None):
        self.info = info
        self.port = int(port)
        self._on_peer_lost = on_peer_lost
        self._closing = threading.Event()
        self._ctl = {}     # peer process id -> ctl socket
        self._watch = {}   # peer process id -> watch socket
        self._ctl_lock = threading.Lock()
        self._fetch_seq = 0   # single fetch-driver thread per process
        # the channel is rooted on the leader's machine — process 0 IS
        # the coordinator host, so followers dial the coordinator's
        # address (a hardcoded loopback would strand every genuinely
        # multi-machine pod), and the leader binds THAT address, so a
        # loopback-coordinated local cluster never listens off-box
        host = "127.0.0.1"
        if info.coordinator:
            host = str(info.coordinator).rsplit(":", 1)[0] or host
        self._token = _channel_token(info)
        if info.is_leader:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                srv.bind((host, self.port))
            except OSError:
                # the coordinator name may not be a bindable local
                # address in some container/NAT setups; fall back to
                # all interfaces (the authenticated hello still gates
                # who gets a peer slot)
                srv.bind(("", self.port))
            srv.listen(2 * info.num_processes)
            srv.settimeout(timeout_s)
            self._srv = srv
            need = 2 * (info.num_processes - 1)
            deadline = time.monotonic() + timeout_s
            got = 0
            while got < need:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"pod channel: {need - got} follower socket(s) "
                        f"never connected within {timeout_s}s")
                conn, _ = srv.accept()
                # accept()ed sockets are blocking regardless of the
                # listener timeout; a peer that connects but never
                # sends its hello (stray scanner, wedged follower)
                # must hit the bootstrap deadline, not hang forever
                conn.settimeout(max(0.1, deadline - time.monotonic()))
                try:
                    raw = _recv_exact(conn, _HELLO.size + _HELLO_MAC)
                except (OSError, PodPeerLost):
                    # not a follower (or a dead one): drop it and keep
                    # accepting — the deadline check above still turns
                    # a missing peer into the advertised TimeoutError
                    conn.close()
                    continue
                head, mac = raw[:_HELLO.size], raw[_HELLO.size:]
                want = hmac.new(self._token, b"pss-pod-hello" + head,
                                hashlib.sha256).digest()
                kbyte, pid = _HELLO.unpack(head)
                store = self._ctl if kbyte == b"c" else self._watch
                if not hmac.compare_digest(mac, want) or pid in store:
                    # forged/garbled hello, or a slot already filled by
                    # an authenticated peer: never let it displace (or
                    # satisfy the count for) a real follower
                    conn.close()
                    continue
                conn.settimeout(None)
                store[pid] = conn
                got += 1
        else:
            self._srv = None
            for kind, store in (("ctl", self._ctl), ("watch", self._watch)):
                store[0] = self._connect(host, kind, timeout_s)
        self._watchers = []
        for pid, sock in self._watch.items():
            t = threading.Thread(target=self._watch_peer, args=(pid, sock),
                                 daemon=True, name=f"pss-pod-watch-{pid}")
            t.start()
            self._watchers.append(t)

    def _connect(self, host, kind, timeout_s):
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                s = socket.create_connection((host, self.port), timeout=5.0)
                s.settimeout(None)
                s.sendall(_hello_frame(kind, self.info.process_id,
                                       self._token))
                return s
            except OSError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"pod channel: leader at port {self.port} never "
                        f"accepted within {timeout_s}s")
                time.sleep(0.05)

    # -- watchdog ----------------------------------------------------------

    def _watch_peer(self, pid, sock):
        # read until EOF or the full shutdown frame: TCP may fragment
        # the tiny _BYE (cross-host pods especially), and a partial
        # first recv must not be mistaken for peer death
        data = b""
        try:
            while len(data) < len(_BYE):
                chunk = sock.recv(len(_BYE) - len(data))
                if not chunk:
                    break
                data += chunk
        except OSError:
            pass
        if data == _BYE:
            return
        self._peer_dead(pid)

    def _peer_dead(self, pid):
        """One reaction to peer death for BOTH detection paths (the
        watch stream's EOF and a :class:`PodPeerLost` on the ctl
        stream): the exit-code contract (``POD_PEER_EXIT``, never an
        arbitrary unwind's rc) must not depend on which thread notices
        first."""
        if self._closing.is_set():
            return   # clean teardown: EOFs are expected
        if self._on_peer_lost is not None:
            self._on_peer_lost(pid)
            return
        print(f"pod: peer process {pid} died (channel EOF); aborting "
              f"this program group for a clean supervisor restart",
              file=sys.stderr, flush=True)
        sys.stderr.flush()
        os._exit(POD_PEER_EXIT)

    # -- control traffic ---------------------------------------------------

    def next_fetch_seq(self):
        """The per-process monotonic fetch counter stamped onto every
        :func:`_channel_fetch` frame (the documented single-owner rule:
        one thread per process drives fetches, so no lock)."""
        self._fetch_seq += 1
        return self._fetch_seq

    def broadcast(self, obj):
        """Leader -> every follower (one frame each, FIFO per peer)."""
        payload = pickle.dumps(obj, protocol=4)
        with self._ctl_lock:
            for sock in self._ctl.values():
                _send_frame(sock, payload)

    def send_to(self, pid, obj):
        """Leader -> ONE follower (FIFO on that peer's ctl stream) —
        the per-peer half of the complement fetch exchange."""
        payload = pickle.dumps(obj, protocol=4)
        with self._ctl_lock:
            _send_frame(self._ctl[pid], payload)

    def recv(self):
        """Follower: the next leader frame (blocks)."""
        try:
            return pickle.loads(_recv_frame(self._ctl[0]))
        except PodPeerLost:
            # ctl EOF races the watch stream's EOF on a dead peer; take
            # the SAME deterministic exit path rather than let whichever
            # thread is scheduled first pick the process's exit code
            self._peer_dead(0)
            raise

    def send_to_leader(self, obj):
        _send_frame(self._ctl[0], pickle.dumps(obj, protocol=4))

    def gather(self):
        """Leader: one frame from EVERY follower -> {pid: obj}."""
        out = {}
        for pid, sock in self._ctl.items():
            try:
                out[pid] = pickle.loads(_recv_frame(sock))
            except PodPeerLost:
                self._peer_dead(pid)
                raise
        return out

    def barrier(self, tag="sync", timeout_s=120.0):
        """All processes rendezvous: followers report in, the leader
        acks.  (Leader-rooted, like everything on this channel.)"""
        if self.info.is_leader:
            for pid, got in self.gather().items():
                if got != ("barrier", tag):
                    raise RuntimeError(
                        f"pod barrier {tag!r}: peer {pid} sent {got!r} "
                        "(program groups out of lockstep)")
            self.broadcast(("barrier-ack", tag))
        else:
            self.send_to_leader(("barrier", tag))
            got = self.recv()
            if got != ("barrier-ack", tag):
                raise RuntimeError(
                    f"pod barrier {tag!r}: leader sent {got!r} "
                    "(program groups out of lockstep)")

    def close(self):
        """Clean shutdown: BYE on every watch socket, close everything.
        Idempotent."""
        if self._closing.is_set():
            return
        self._closing.set()
        for sock in self._watch.values():
            try:
                sock.sendall(_BYE)
            except OSError:
                pass
        for sock in list(self._ctl.values()) + list(self._watch.values()):
            try:
                sock.close()
            except OSError:
                pass
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass


def pod_health():
    """JSON-ready pod status for /healthz-style consumers."""
    info = _pod.describe()
    info["channel"] = _channel is not None
    return info


def _reset_for_tests():  # psrlint: disable=PSR105 (the pod lifecycle)
    """TESTS ONLY: forget the pod state (the solo fallback returns).
    Does not tear down jax.distributed — only meaningful in processes
    that never initialized it (fake-topology registry audits)."""
    global _pod, _channel
    if _channel is not None:
        _channel.close()
    _pod = _SOLO
    _channel = None


def fake_pod_for_tests(num_processes, process_id=0):  # psrlint: disable=PSR105
    """TESTS ONLY: install a :class:`PodInfo` WITHOUT touching jax —
    the simulated topology the registry/cache key audit runs across
    (program keys must fork on topology even where no real cluster can
    exist, e.g. inside one pytest process).  Returns the previous state
    so callers can restore it."""
    global _pod
    prev = _pod
    _pod = PodInfo(process_id=process_id, num_processes=num_processes,
                   initialized=True)
    return prev
