"""End-to-end integrity: the silent-corruption defense of every durable path.

Every sha256 this repo journals (export files, MC trial chunks, dataset
record chunks, cache artifacts) is computed on the HOST, *after* the
bytes left the device — so a bit flipped by device compute (SDC: silent
data corruption, a real failure mode on large accelerator fleets), in
host memory between fetch and encode, or by disk bit-rot after commit is
journaled as "good" and served forever.  This module closes those
windows with three layers:

1. **Checksum lattice** — a cheap exact uint32 digest (positional
   multiply-xor-sum fold over the quantized int16 codes or the bitcast
   float words) computed ON DEVICE over each fused chunk's output buffer
   before it crosses the host link, then recomputed on the host from the
   fetched bytes at the point the producer consumes them.  The device
   and host folds are bit-identical modular uint32 arithmetic, so any
   disagreement is corruption in the fetch->consume window — the journal
   record becomes a device-attested claim instead of a host-attested
   one.  (The remaining consume->disk window is covered by the existing
   in-memory sha256 of the committed bytes; see docs/robustness.md.)
2. **Duplicate-execution audit** — a deterministic, fingerprint-seeded
   ``audit_frac`` of chunks (default 2%, ``PSS_INTEGRITY_AUDIT_FRAC``)
   is re-dispatched at full chunk width through a FRESH compiled
   instance of the same-physics program (same jaxpr -> same HLO, so the
   bytes must agree) and compared digest-for-digest.  A disagreement is
   the SDC case the lattice cannot see (the digest of wrong bytes
   matches the wrong bytes): the heal contract
   (:meth:`IntegrityChecker.heal_verified`) then requires two
   independent re-executions to agree with each other AND with the
   host re-digest of the bytes being adopted — agreed bytes replace
   the chunk (byte-identical to a clean run — healing never re-draws),
   the event is journaled, and the sticky ``sdc_suspect`` health flag
   the fleet's breaker/eject path can act on is set.  A disagreement
   that SURVIVES re-execution is permanent (:class:`IntegrityError`,
   never retried — see
   :class:`~psrsigsim_tpu.runtime.retry.RetryPolicy` classification).
3. **Self-healing scrub** — incremental re-hash of committed artifacts
   against their journaled sha256: the serving cache drops-and-journals
   corrupt artifacts (recommitted on the next request), export dirs
   quarantine corrupt files aside so the next resume re-runs them, and
   MC/dataset dirs surface corrupt chunks that the existing
   sha-verifying resume paths recompute.  Bit-rot is found before a
   reader is.

Injection points (armed only by an explicit
:class:`~psrsigsim_tpu.runtime.faults.FaultPlan`): ``device.sdc``
perturbs one chunk's device output (only the audit can catch it),
``host.corrupt`` flips a fetched buffer pre-encode (the lattice catches
it), ``disk.bitrot`` flips a committed artifact's bytes (the scrub
catches it).  tests/test_faults.py drives the full matrix across every
producer.

Everything here is OFF by default: with ``integrity=None`` and
``PSS_INTEGRITY`` unset, no digest program is ever built and every
producer takes exactly its pre-existing code path (compiled programs
are jaxpr-identical to a build without this module).
"""

from __future__ import annotations

import hashlib
import os
import threading

import numpy as np

from .retry import RetryPolicy, call_with_retry

__all__ = [
    "IntegrityChecker", "IntegrityError", "resolve_integrity",
    "digest_rows", "digest_array", "device_digest_rows",
    "device_packed_digest_rows", "triple_digest_rows",
    "audit_selected", "DEFAULT_AUDIT_FRAC",
    "maybe_sdc", "maybe_host_corrupt", "maybe_bitrot",
    "DirScrubber", "scrub_export_dir", "scrub_mc_dir", "scrub_dataset_dir",
]

#: default duplicate-execution audit fraction once integrity is enabled
#: (``PSS_INTEGRITY_AUDIT_FRAC`` overrides; 0 disables auditing while
#: keeping the checksum lattice)
DEFAULT_AUDIT_FRAC = 0.02

# digest constants (Knuth/Murmur-style odd multipliers); the fold is
#   sum_i ((w_i ^ m_i) * GOLD + m_i)  mod 2^32,  m_i = (i+salt)*GOLD + OFF
# — positional (catches swapped words), exact (pure modular integer
# arithmetic, so host numpy and device XLA agree bit for bit), and one
# multiply-add per word (cheap next to the pipeline it guards)
_GOLD = 0x9E3779B1
_OFF = 0x85EBCA77
_MASK = 0xFFFFFFFF

# component salts of a (data, scl, offs) quantized triple digest — the
# three streams fold with disjoint positional multipliers so a value
# migrating between components cannot cancel
_SALT_DATA, _SALT_SCL, _SALT_OFFS = 0, 1 << 20, 2 << 20


class IntegrityError(RuntimeError):
    """A corruption that survived its one verified re-execution.

    PERMANENT by classification: re-running cannot help (two independent
    executions already disagree with each other and with the original),
    so retry loops must fail fast instead of burning their backoff
    budget — :func:`~psrsigsim_tpu.runtime.retry.call_with_retry`
    re-raises it immediately when the policy classifies it permanent.
    :attr:`evidence` carries the audit trail (producer, chunk start,
    the disagreeing digests) for the operator."""

    def __init__(self, message, evidence=None):
        self.evidence = dict(evidence or {})
        if self.evidence:
            message = f"{message} [evidence: {self.evidence}]"
        super().__init__(message)


# ---------------------------------------------------------------------------
# the digest fold — host (numpy) and device (jnp) twins
# ---------------------------------------------------------------------------


def _host_words_u32(arr):
    """Elementwise uint32 words of a host array: float32 bitcast, 64-bit
    dtypes reinterpreted as word pairs, integers value-converted with
    C wrap semantics — each exactly what the device twin computes."""
    a = np.asarray(arr)
    if a.dtype == np.float32:
        return np.ascontiguousarray(a).view(np.uint32)
    if a.dtype.itemsize == 8:
        return np.ascontiguousarray(a).view(np.uint32)
    if a.dtype.kind in "iub":
        return a.astype(np.uint32)
    raise TypeError(f"undigestable dtype {a.dtype}")


def _fold_u32(words, salt):
    """The modular fold over a (rows, n) uint32 word matrix -> (rows,)
    uint32.  Host arithmetic runs in uint64 and masks, which equals the
    device's wrapping uint32 arithmetic exactly."""
    w = words.astype(np.uint64)
    n = w.shape[-1]
    m = ((np.arange(n, dtype=np.uint64) + np.uint64(salt & _MASK))
         * np.uint64(_GOLD) + np.uint64(_OFF)) & np.uint64(_MASK)
    terms = (((w ^ m) * np.uint64(_GOLD)) + m) & np.uint64(_MASK)
    return (terms.sum(axis=-1, dtype=np.uint64) & np.uint64(_MASK)).astype(
        np.uint32)


def digest_rows(arr, salt=0):
    """Per-row host digest of ``arr`` (leading axis = rows): ``(B,)``
    uint32, bit-identical to :func:`device_digest_rows` on the same
    logical values."""
    a = np.asarray(arr)
    if a.ndim == 0:
        raise ValueError("digest_rows needs at least one axis")
    w = _host_words_u32(a).reshape(a.shape[0], -1)
    return _fold_u32(w, salt)


def digest_array(arr, salt=0):
    """Whole-array host digest (one uint32 as a python int)."""
    a = np.asarray(arr)
    return int(digest_rows(a.reshape(1, -1), salt)[0])


def triple_digest_rows(data, scl, offs):
    """Per-observation host digest of a quantized ``(data, scl, offs)``
    triple: the three component folds (disjoint salts) summed mod 2^32.
    ``data`` must be NATIVE int16 (digest before any ``.view('>i2')`` —
    a byte-order view changes values, and the device digested the
    native values of the packed buffer)."""
    d = digest_rows(data, _SALT_DATA)
    s = digest_rows(np.ascontiguousarray(scl, np.float32), _SALT_SCL)
    o = digest_rows(np.ascontiguousarray(offs, np.float32), _SALT_OFFS)
    return ((d.astype(np.uint64) + s + o) & np.uint64(_MASK)).astype(
        np.uint32)


def _dev_fold_u32(words, salt):
    """Device twin of :func:`_fold_u32` (traced; uint32 wraps mod 2^32
    by construction)."""
    import jax.numpy as jnp

    n = words.shape[-1]
    idx = jnp.arange(n, dtype=jnp.uint32)
    m = (idx + jnp.uint32(salt & _MASK)) * jnp.uint32(_GOLD) \
        + jnp.uint32(_OFF)
    terms = ((words ^ m) * jnp.uint32(_GOLD)) + m
    return jnp.sum(terms, axis=-1, dtype=jnp.uint32)


def _dev_words_u32(x):
    import jax
    import jax.numpy as jnp

    if x.dtype == jnp.float32:
        return jax.lax.bitcast_convert_type(x, jnp.uint32)
    if x.dtype.itemsize == 8:
        # 64-bit elements bitcast to uint32 word pairs (a trailing axis
        # of 2, little-endian word order) — exactly the host twin's
        # ``view(np.uint32)`` reinterpretation, NOT a value truncation
        return jax.lax.bitcast_convert_type(x, jnp.uint32)
    return x.astype(jnp.uint32)


def _digest_rows_traced(x, salt=0):
    """Traced per-row digest: the body every digest program jits."""
    w = _dev_words_u32(x).reshape(x.shape[0], -1)
    return _dev_fold_u32(w, salt)


def _digest_program(kind, builder):
    """Resolve a jitted digest program through the shared registry so
    build counts stay visible; one program per kind, retracing per input
    shape (chunk shapes are fixed per run, so one trace each)."""
    import jax

    from .programs import global_registry, trace_env_key

    return global_registry().get_or_build(
        ("integrity_digest", kind, trace_env_key()),
        lambda: jax.jit(builder))


def device_digest_rows(x, salt=0):
    """Per-row digest of a DEVICE array, computed on device (one tiny
    dispatch over the already-resident buffer — the attestation happens
    before any byte crosses the host link).  Returns a device ``(B,)``
    uint32 array; fetch it alongside the chunk."""
    kind = "rows" if not salt else f"rows{salt}"  # distinct programs
    return _digest_program(
        kind, lambda a, _s=salt: _digest_rows_traced(a, _s))(x)


def device_packed_digest_rows(packed, nbin):
    """Per-observation device digest of a fused-transport packed chunk
    ``(B, nsub, C, nbin+4)`` int16: the data slice and the bitcast
    scl/offs tail words fold with the SAME salts as the host
    :func:`triple_digest_rows` of the split triple — so the host
    re-check needs only the split arrays every consumer already holds."""
    import jax
    import jax.numpy as jnp

    def _fn(p):
        data = p[..., :nbin]
        scl_u = jax.lax.bitcast_convert_type(
            p[..., nbin:nbin + 2], jnp.uint32)
        offs_u = jax.lax.bitcast_convert_type(
            p[..., nbin + 2:nbin + 4], jnp.uint32)
        d = _digest_rows_traced(data, _SALT_DATA)
        s = _dev_fold_u32(scl_u.reshape(p.shape[0], -1), _SALT_SCL)
        o = _dev_fold_u32(offs_u.reshape(p.shape[0], -1), _SALT_OFFS)
        return d + s + o

    return _digest_program(f"packed{nbin}", _fn)(packed)


def fields_digest_rows_host(arrays):
    """Combined per-record host digest of a tuple of per-field arrays
    (the dataset chunk layout): each field folds with its own salt,
    summed mod 2^32."""
    total = np.zeros(np.asarray(arrays[0]).shape[0], np.uint64)
    for f, a in enumerate(arrays):
        total = (total + digest_rows(a, salt=(f + 1) << 16)) \
            & np.uint64(_MASK)
    return total.astype(np.uint32)


def device_fields_digest_rows(arrays):
    """Device twin of :func:`fields_digest_rows_host` (one dispatch over
    the chunk's field buffers)."""
    def _fn(*devs):
        total = None
        for f, a in enumerate(devs):
            d = _digest_rows_traced(a, salt=(f + 1) << 16)
            total = d if total is None else total + d
        return total

    return _digest_program(f"fields{len(arrays)}", _fn)(*arrays)


# ---------------------------------------------------------------------------
# audit sampling
# ---------------------------------------------------------------------------


def audit_selected(fingerprint, ident, frac):
    """Deterministic fingerprint-seeded chunk sampling: chunk ``ident``
    of the run fingerprinted ``fingerprint`` is audited iff the leading
    64 bits of ``sha256(fingerprint|ident)`` fall below ``frac`` — the
    same chunks audit on every resume of the same run (so a kill/resume
    cannot dodge its audits), different runs audit different chunks."""
    frac = float(frac)
    if frac <= 0.0:
        return False
    if frac >= 1.0:
        return True
    h = hashlib.sha256(f"{fingerprint}|{ident}".encode()).digest()
    return int.from_bytes(h[:8], "big") < int(frac * 2.0 ** 64)


# ---------------------------------------------------------------------------
# fault helpers (device.sdc / host.corrupt / disk.bitrot)
# ---------------------------------------------------------------------------


def _ident_matches(cfg, ident):
    after = cfg.get("after_start")
    return after is None or (ident is not None and int(after) == int(ident))


def maybe_sdc(plan, dev, token="", ident=None):
    """``device.sdc`` injection: return the device buffer with ONE
    element perturbed (+1 on the int16 code / +1.0 on the float word at
    the origin) — the device "computed" wrong bytes, so every digest of
    this buffer attests the wrong bytes and only duplicate execution
    can notice.  Config: ``{"after_start": int}`` (chunk start) plus
    the usual ``match``/``times``."""
    if plan is None:
        return dev
    cfg = plan.config("device.sdc")
    if cfg is None or not _ident_matches(cfg, ident):
        return dev
    if not plan.fire("device.sdc", token=token):
        return dev
    origin = (0,) * dev.ndim
    bump = 1.0 if dev.dtype.kind == "f" else 1
    return dev.at[origin].add(bump)


def maybe_host_corrupt(plan, arr, token="", ident=None):
    """``host.corrupt`` injection: flip one element of a FETCHED host
    buffer (the fetch->encode window the checksum lattice closes).
    Returns the buffer to use downstream — the same object when
    unarmed, a corrupted copy when the point fired (fetched device
    buffers are read-only views, exactly like the real corruption
    victim: the corruption happens to the memory, not through the
    array API)."""
    if plan is None:
        return arr
    cfg = plan.config("host.corrupt")
    if cfg is None or not _ident_matches(cfg, ident):
        return arr
    if not plan.fire("host.corrupt", token=token):
        return arr
    a = np.array(arr)   # writable copy standing in for the flipped page
    origin = (0,) * a.ndim
    if a.dtype.kind == "f":
        # flip the mantissa LSB of the first word: unlike adding a
        # constant, a bit flip changes the pattern for EVERY value
        u = a.view(np.uint32 if a.dtype.itemsize == 4 else np.uint64)
        u[(0,) * u.ndim] ^= 1
    else:
        a[origin] = a[origin] ^ 1
    return a


def maybe_bitrot(plan, path, token=None, offset=None):
    """``disk.bitrot`` injection: XOR one byte of a COMMITTED file
    (after its sha256 was journaled), the decay the scrub layer exists
    to find.  Token defaults to the basename so ``match`` can target
    one artifact; ``offset`` defaults to the middle of the file
    (positional-slot formats pass the committed chunk's own offset so
    the flip lands in journaled bytes).  Returns True when it fired."""
    if plan is None:
        return False
    cfg = plan.config("disk.bitrot")
    if cfg is None:
        return False
    if not plan.fire("disk.bitrot",
                     token=os.path.basename(path) if token is None
                     else token):
        return False
    size = os.path.getsize(path)
    if size == 0:
        return False
    pos = size // 2 if offset is None else min(int(offset), size - 1)
    with open(path, "rb+") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))
        f.flush()
        os.fsync(f.fileno())
    return True


# ---------------------------------------------------------------------------
# the checker: per-run integrity state
# ---------------------------------------------------------------------------


def _env_enabled():
    return os.environ.get("PSS_INTEGRITY", "").lower() in ("1", "on",
                                                           "true", "yes")


def _env_audit_frac():
    try:
        return float(os.environ.get("PSS_INTEGRITY_AUDIT_FRAC",
                                    DEFAULT_AUDIT_FRAC))
    except ValueError:
        return DEFAULT_AUDIT_FRAC


class IntegrityChecker:
    """One run's integrity configuration + counters.

    Producers hold one checker per run (export, study sweep, corpus
    write, serving engine) and report through it; its :meth:`stats`
    land in manifests, ``/metrics`` and ``health()``.  Thread-safe
    (the serving batcher and scrub heartbeat share one).

    Parameters
    ----------
    audit_frac : float
        Duplicate-execution audit fraction (0 disables the audit but
        keeps the checksum lattice).  Default:
        ``PSS_INTEGRITY_AUDIT_FRAC`` (2%).
    fingerprint : str
        Seed of the deterministic audit sampling — the run's own
        fingerprint digest, so resumes audit the same chunks.
    faults : FaultPlan, optional
        Arms ``device.sdc`` / ``host.corrupt`` / ``disk.bitrot``.
    """

    def __init__(self, audit_frac=None, fingerprint="", faults=None):
        self.audit_frac = (_env_audit_frac() if audit_frac is None
                           else float(audit_frac))
        if not 0.0 <= self.audit_frac <= 1.0:
            raise ValueError("audit_frac must be in [0, 1]")
        self.fingerprint = str(fingerprint)
        self.faults = faults
        self._lock = threading.Lock()
        self.checks = 0               # host-vs-device checksum compares
        self.checksum_mismatches = 0  # host.corrupt-window detections
        self.audits = 0               # duplicate executions run
        self.audit_mismatches = 0     # device-disagreement detections
        self.healed_chunks = 0        # chunks replaced by verified bytes
        self.permanent_failures = 0   # IntegrityError raised
        self.sdc_suspect = False      # sticky: device disagreed with its
        #                               own re-execution at least once

    # -- sampling / fault arms --------------------------------------------

    def audit_chunk(self, ident):
        return audit_selected(self.fingerprint, ident, self.audit_frac)

    def apply_sdc(self, dev, ident=None, token=None):
        return maybe_sdc(self.faults, dev,
                         token=f"start={ident}" if token is None else token,
                         ident=ident)

    def corrupt_host(self, arr, ident=None, token=None):
        """Apply the ``host.corrupt`` arm; returns the buffer to use
        downstream (a corrupted copy when the point fired)."""
        return maybe_host_corrupt(
            self.faults, arr,
            token=f"start={ident}" if token is None else token, ident=ident)

    # -- verdicts ----------------------------------------------------------

    def check_rows(self, device_digests, host_digests, ident=None,
                   producer=""):
        """Compare fetched device digests against the host recompute;
        returns the mismatching row indices (empty = the fetch->consume
        window was clean)."""
        dev = np.asarray(device_digests, np.uint32).reshape(-1)
        host = np.asarray(host_digests, np.uint32).reshape(-1)
        n = min(dev.size, host.size)
        bad = np.nonzero(dev[:n] != host[:n])[0]
        with self._lock:
            self.checks += 1
            if bad.size:
                self.checksum_mismatches += 1
        return [int(j) for j in bad]

    def note_audit(self, mismatch_rows):
        with self._lock:
            self.audits += 1
            if mismatch_rows:
                self.audit_mismatches += 1
                self.sdc_suspect = True

    def note_healed(self):
        with self._lock:
            self.healed_chunks += 1

    def fail_permanent(self, message, evidence=None):
        with self._lock:
            self.permanent_failures += 1
            self.sdc_suspect = True
        raise IntegrityError(message, evidence)

    def heal_verified(self, reexecute, verify, *, producer, ident,
                      evidence=None):
        """Run ``reexecute()`` and require ``verify(result) -> True`` —
        the heal contract every producer shares: a fresh execution whose
        own device/host digests agree replaces the corrupt chunk; a
        verification that fails even on re-execution is PERMANENT and
        fails fast with the evidence attached (the retry-classification
        contract: one transient re-execute is budgeted, an integrity
        mismatch that survives it never burns backoff)."""
        def _attempt():
            out = reexecute()
            if not verify(out):
                self.fail_permanent(
                    f"{producer}: re-executed chunk {ident} failed its own "
                    "digest verification", evidence)
            return out

        out = call_with_retry(
            _attempt,
            RetryPolicy(max_attempts=2, base_delay=0.0,
                        permanent_on=(IntegrityError,)))
        self.note_healed()
        return out

    # -- reporting ---------------------------------------------------------

    def stats(self):
        with self._lock:
            return {
                "audit_frac": self.audit_frac,
                "checks": self.checks,
                "checksum_mismatches": self.checksum_mismatches,
                "audits": self.audits,
                "audit_mismatches": self.audit_mismatches,
                "healed_chunks": self.healed_chunks,
                "permanent_failures": self.permanent_failures,
                "sdc_suspect": self.sdc_suspect,
            }

    def __repr__(self):
        return (f"IntegrityChecker(audit_frac={self.audit_frac}, "
                f"checks={self.checks}, audits={self.audits}, "
                f"sdc_suspect={self.sdc_suspect})")


def resolve_integrity(integrity, fingerprint="", faults=None):
    """The one arming rule every producer shares.

    ``integrity`` may be: None (consult ``PSS_INTEGRITY`` — unset means
    OFF, the zero-cost default), False (force off), True (on with env/
    default audit fraction), a float (on with that audit fraction), or
    an :class:`IntegrityChecker` (used as-is; an unset fingerprint or
    fault plan is stamped from the call site so the checker follows the
    run it guards).  Returns a checker or None."""
    if integrity is None:
        if not _env_enabled():
            return None
        integrity = True
    if integrity is False:
        return None
    if integrity is True:
        return IntegrityChecker(fingerprint=fingerprint, faults=faults)
    if isinstance(integrity, (int, float)) and not isinstance(
            integrity, bool):
        return IntegrityChecker(audit_frac=float(integrity),
                                fingerprint=fingerprint, faults=faults)
    if isinstance(integrity, IntegrityChecker):
        if not integrity.fingerprint:
            integrity.fingerprint = str(fingerprint)
        if integrity.faults is None:
            integrity.faults = faults
        return integrity
    raise TypeError(f"integrity must be None/bool/float/IntegrityChecker, "
                    f"got {integrity!r}")


# ---------------------------------------------------------------------------
# the scrub layer
# ---------------------------------------------------------------------------


def _file_sha256(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


class DirScrubber:
    """Incremental scrubber over a ``{basename: sha256}`` record (an
    export manifest's ``files`` map): :meth:`step` re-hashes a bounded
    number of files per call — the per-heartbeat budget that keeps the
    scrub off any latency path — rotating through the record forever.

    A mismatched file is QUARANTINED (renamed ``<name>.quarantine``) so
    even a plain existence-keyed resume re-runs it; a hash-verified
    resume would also catch it, but quarantine means the very next
    resume heals regardless of its verify mode."""

    def __init__(self, out_dir, hashes, quarantine=True):
        self.out_dir = str(out_dir)
        self.hashes = dict(hashes)
        self.quarantine = bool(quarantine)
        self._ring = sorted(self.hashes)
        self._pos = 0
        self.scrubbed = 0      # files re-hashed clean
        self.scrub_errors = 0  # mismatches found (and quarantined)
        self.bad = []          # basenames that failed

    def step(self, max_files=1):
        """Re-hash up to ``max_files`` committed files; returns the list
        of basenames found corrupt THIS step."""
        found = []
        for _ in range(int(max_files)):
            if not self._ring:
                return found
            name = self._ring[self._pos % len(self._ring)]
            self._pos += 1
            path = os.path.join(self.out_dir, name)
            try:
                ok = _file_sha256(path) == self.hashes[name]
            except OSError:
                continue   # missing: resume already treats it as undone
            if ok:
                self.scrubbed += 1
                continue
            self.scrub_errors += 1
            self.bad.append(name)
            found.append(name)
            if self.quarantine:
                try:
                    os.replace(path, path + ".quarantine")
                except OSError:
                    pass
        return found

    def run_all(self):
        """One full pass over the record; returns the summary dict."""
        self.step(max_files=len(self._ring))
        return {"scanned": self.scrubbed + self.scrub_errors,
                "scrubbed": self.scrubbed,
                "scrub_errors": self.scrub_errors,
                "bad": list(self.bad)}


def scrub_export_dir(out_dir, quarantine=True):
    """One full scrub pass over a supervised export's manifest record:
    re-hash every committed file against its journaled sha256 and
    quarantine mismatches aside (``*.quarantine``) so the next
    ``supervised_export(..., resume=True)`` re-runs exactly those
    observations — detection here, heal on resume, bytes identical to a
    never-rotted run."""
    from ..io.export import _load_manifest

    man = _load_manifest(out_dir) or {}
    return DirScrubber(out_dir, man.get("files", {}),
                       quarantine=quarantine).run_all()


def scrub_mc_dir(out_dir):
    """Scrub a study sweep dir: re-hash every journaled trial chunk's
    rows from ``trials.f32`` against the journal sha.  Returns the
    summary with ``bad`` = corrupt chunk starts; healing is
    ``study.run(resume=True)`` — its resume path re-verifies the same
    hashes and recomputes exactly the failing chunks."""
    from ..mc import study as _study
    from .supervisor import load_chunk_journal

    journal = os.path.join(out_dir, _study._JOURNAL_NAME)
    raw = os.path.join(out_dir, _study._TRIALS_RAW)
    done = load_chunk_journal(journal)
    man_path = os.path.join(out_dir, _study._MANIFEST_NAME)
    import json as _json

    with open(man_path) as f:
        man = _json.load(f)
    n_metrics = len(man.get("metrics", ()))
    bad, ok = [], 0
    try:
        fd = os.open(raw, os.O_RDONLY)
    except FileNotFoundError:
        return {"scanned": 0, "scrubbed": 0, "scrub_errors": 0, "bad": []}
    try:
        for start, rec in sorted(done.items()):
            nbytes = int(rec["count"]) * n_metrics * 4
            blob = os.pread(fd, nbytes, start * n_metrics * 4)
            if (len(blob) == nbytes
                    and hashlib.sha256(blob).hexdigest() == rec.get("sha")):
                ok += 1
            else:
                bad.append(int(start))
    finally:
        os.close(fd)
    return {"scanned": ok + len(bad), "scrubbed": ok,
            "scrub_errors": len(bad), "bad": bad}


def scrub_dataset_dir(out_dir):
    """Scrub a dataset corpus dir: re-hash every journaled record
    chunk's bytes out of the shards against the journal sha.  Returns
    ``bad`` = corrupt chunk starts; healing is
    ``DatasetFactory.run(resume=True)`` — the factory's resume already
    re-hashes journaled chunks from shard bytes and recomputes any that
    fail."""
    import json as _json

    from ..datasets import factory as _factory
    from ..datasets.writer import DatasetReader
    from .supervisor import load_chunk_journal

    journal = os.path.join(out_dir, _factory._JOURNAL_NAME)
    done = load_chunk_journal(journal)
    with open(os.path.join(out_dir, _factory._MANIFEST_NAME)) as f:
        man = _json.load(f)
    del man   # manifest existence is the corpus check; bytes come below
    with DatasetReader(out_dir) as reader:
        stride = reader.stride
        bad, ok = [], 0
        for start, rec in sorted(done.items()):
            h = hashlib.sha256()
            complete = True
            for i in range(start, start + int(rec["count"])):
                buf = reader.record_bytes(i)
                if len(buf) != stride:
                    complete = False
                    break
                h.update(buf)
            if complete and h.hexdigest() == rec.get("sha"):
                ok += 1
            else:
                bad.append(int(start))
    return {"scanned": ok + len(bad), "scrubbed": ok,
            "scrub_errors": len(bad), "bad": bad}
