"""psrlint: static + dynamic correctness gates for the TPU pipeline.

The tier-1 CPU suite proves numerics; it cannot prove *trace hygiene* —
Python branching on traced values, host ``np.`` round-trips inside
jitted ops, reused PRNG keys, float64 leaks, process-global state, and
phantom sharding axes all pass CPU tests and then corrupt or de-scale
the real TPU workload.  This package gates those classes in CI:

* :func:`run_lint` / ``python -m psrsigsim_tpu.analysis`` — AST checkers
  with stable rule IDs (``PSR101``-``PSR106``), inline suppression
  (``# psrlint: disable=RULE``), and a per-(rule, file) count-ratchet
  baseline (``analysis/baseline.txt``).
* :func:`run_trace_check` — traces every public ``ops`` symbol under
  ``jax.make_jaxpr``/``jax.eval_shape`` on canonical shapes and asserts
  a stable jit cache, cross-checking the linter's static claims.

See docs/static_analysis.md for the rule catalog and workflow.
"""

from .core import (Finding, LintConfig, RULES, baseline_regressions,
                   load_baseline, load_config, run_lint, write_baseline)
from .trace_check import EXEMPT, probe_specs, run_trace_check

__all__ = [
    "Finding",
    "LintConfig",
    "RULES",
    "run_lint",
    "load_config",
    "load_baseline",
    "write_baseline",
    "baseline_regressions",
    "run_trace_check",
    "probe_specs",
    "EXEMPT",
]
