"""Dynamic trace probe: cross-check psrlint's static claims at trace time.

Every public symbol in ``psrsigsim_tpu.ops`` is either (a) traced under
``jax.make_jaxpr`` + ``jax.eval_shape`` on a canonical small-shape input
and re-jitted twice to prove a stable cache (retrace count == 1), or
(b) listed in :data:`EXEMPT` with the reason it is host-side by design.
A symbol that is neither is a coverage failure — new ops must register a
probe here the day they are exported (tests/test_psrlint.py enforces
this).

Why both layers: the AST linter reasons about *source*, so a checker bug
or an unanticipated idiom can let a trace-unsafe op slip through; the
probe actually traces each op, so Python branching on tracers, host
``np.`` round-trips on traced values, and shape-dependent retracing all
fail here regardless of what the linter thought.  Runs on CPU
(``JAX_PLATFORMS=cpu``) — tracing is backend-independent.
"""

from __future__ import annotations

__all__ = ["EXEMPT", "probe_specs", "run_trace_check",
           "run_serve_trace_check", "run_dataset_trace_check",
           "ProbeResult"]

from dataclasses import dataclass

#: public ops symbols that are host-side or non-callable by design
EXEMPT = {
    "PchipCoeffs": "interpolant container (NamedTuple), not an op",
    "chi2_draw_norm": "host-side config helper (scipy ppf at staging time)",
    "offpulse_window": "host-side float64 reference-parity variant; "
                       "offpulse_window_jax is the traced twin",
}


@dataclass
class ProbeResult:
    name: str
    status: str       # "ok" | "exempt"
    detail: str = ""


def _specs():
    """name -> (fn, example_args) with every traced argument a jax array.

    Shapes are tiny: the probe checks TRACEABILITY, not numerics (the
    tier-1 suite owns numerics).  Static configuration (nchan, nsub,
    plan geometry, ...) is closed over so only genuinely-traced inputs
    are abstracted.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    from .. import ops

    f = jnp.float32
    key = jax.random.key(0)
    prof = jnp.asarray(np.cos(np.linspace(0, 2 * np.pi, 64)) + 1.0, f)
    block = jnp.asarray(np.random.default_rng(0).normal(size=(3, 64)), f)
    i16 = jnp.asarray(np.arange(96).reshape(4, 3, 8) % 251 - 125, jnp.int16)
    x8 = jnp.arange(8.0, dtype=f)
    y8 = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8)), f)
    coeffs = ops.pchip_fit(x8, y8)

    return {
        "channelize_power": (lambda d: ops.channelize_power(d, 8),
                             (jnp.zeros((2, 256), f),)),
        "fourier_shift": (lambda d, s: ops.fourier_shift(d, s, 0.5),
                          (block, jnp.arange(3.0, dtype=f))),
        "coherent_dedispersion_transfer":
            (lambda dm: ops.coherent_dedispersion_transfer(
                64, dm, 1400.0, 200.0, 1.0), (jnp.asarray(10.0, f),)),
        "coherent_dedisperse":
            (lambda d, dm: ops.coherent_dedisperse(
                d, dm, 1400.0, 200.0, 1.0), (block, jnp.asarray(10.0, f))),
        "pchip_slopes": (ops.pchip_slopes, (x8, y8)),
        "pchip_fit": (ops.pchip_fit, (x8, y8)),
        "pchip_eval": (ops.pchip_eval, (coeffs, jnp.linspace(0.0, 7.0, 16))),
        "chi2_sample": (lambda k: ops.chi2_sample(k, 100.0, (32,)), (key,)),
        "normal_sample": (lambda k: ops.normal_sample(k, (32,)), (key,)),
        "fftfit_shift": (ops.fftfit_shift, (prof, prof)),
        "fftfit_batch": (ops.fftfit_batch, (jnp.stack([prof, prof]), prof)),
        "fftfit_combine": (ops.fftfit_combine,
                           (jnp.asarray([0.1, -0.05, 0.02], f),
                            jnp.asarray([0.01, 0.02, 0.01], f))),
        "fixed_histogram": (lambda x: ops.fixed_histogram(x, -1.0, 1.0, 8),
                            (block[0],)),
        "scint_gain": (lambda k, fr, dnu, dt, m: ops.scint_gain(
            k, fr, 4, dnu, dt, m, 1400.0, 0.5),
            (key, jnp.linspace(1200.0, 1600.0, 3, dtype=f),
             jnp.asarray(20.0, f), jnp.asarray(0.5, f),
             jnp.asarray(1.0, f))),
        "rfi_levels": (lambda k, c, ip, ia, np_, na: ops.rfi_levels(
            k, c, 4, ip, ia, np_, na),
            (key, jnp.arange(3), jnp.asarray(0.5, f), jnp.asarray(5.0, f),
             jnp.asarray(0.5, f), jnp.asarray(3.0, f))),
        # static mode choice: every mode is its own program; the probe
        # covers the symbol once per mode so a trace-unsafe edit to any
        # branch fails here
        "pulse_energies": (lambda k, s: tuple(
            ops.pulse_energies(k, 4, mode, s)
            for mode in ("lognormal", "powerlaw", "frb")),
            (key, jnp.asarray(0.5, f))),
        "block_downsample": (lambda d: ops.block_downsample(d, 4), (block,)),
        "rebin": (lambda d: ops.rebin(d, 16), (block,)),
        "clip_cast": (lambda b: ops.clip_cast(b, 200.0), (block,)),
        "subint_quantize": (lambda b: ops.subint_quantize(b, 4, 16),
                            (block,)),
        "subint_dequantize": (ops.subint_dequantize,
                              (i16, jnp.ones((4, 3), f),
                               jnp.zeros((4, 3), f))),
        "swap16": (ops.swap16, (i16,)),
        "fft_convolve_full": (ops.fft_convolve_full, (block, block)),
        "convolve_profiles": (lambda p, k: ops.convolve_profiles(p, k, 64),
                              (block, block)),
        "fold_periods": (lambda d: ops.fold_periods(d, 16), (block,)),
        "offpulse_window_jax": (ops.offpulse_window_jax, (prof,)),
        "offpulse_window_indices":
            (lambda: ops.offpulse_window_indices(64), ()),
    }


def probe_specs():
    """The probe table (imports jax on first use)."""
    return _specs()


def _check_one(name, fn, args):
    """Trace, abstract-eval, and retrace-count one op; raises on failure."""
    import jax

    jax.make_jaxpr(fn)(*args)
    jax.eval_shape(fn, *args)

    traces = [0]

    def counting(*a):
        traces[0] += 1
        return fn(*a)

    jitted = jax.jit(counting)
    jitted(*args)
    jitted(*args)
    if traces[0] != 1:
        raise AssertionError(
            f"{name}: traced {traces[0]} times for one call signature — "
            "something in it depends on concrete values or fresh Python "
            "identity per call")


def run_serve_trace_check(widths=(1, 8)):
    """Probe the serving layer's width-bucketed batch programs
    (:func:`psrsigsim_tpu.parallel.build_width_bucket_fn` over a
    canonical tiny geometry): ``make_jaxpr`` + ``eval_shape`` + a stable
    jit cache (retrace count == 1) at each probed bucket width — the
    dynamic twin of the serving registry's AOT single-compile guard,
    run where the linter gate runs so a trace-unsafe edit to the fold
    core or the batch wrapper fails CI before it reaches a server.
    """
    import numpy as np

    import jax

    from ..parallel.ensemble import build_width_bucket_fn
    from ..serve.spec import build_geometry, canonicalize

    canonical = canonicalize({
        "nchan": 2, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
        "sample_rate_mhz": 0.2048, "sublen_s": 0.5, "tobs_s": 1.0,
        "period_s": 0.005, "smean_jy": 0.05, "seed": 0, "dm": 10.0,
    })
    cfg, profiles, _ = build_geometry(canonical)
    fn = build_width_bucket_fn(cfg, profiles)
    results = []
    for w in widths:
        keys = jax.vmap(jax.random.key)(np.arange(w, dtype=np.uint32))
        z = np.zeros(w, np.float32)
        _check_one(f"serve_width_bucket[w={w}]", fn, (keys, z, z, z))
        results.append(ProbeResult(f"serve_width_bucket[w={w}]", "ok"))
    return results


def run_dataset_trace_check():
    """Probe the dataset factory's record sampler: the labeled-record
    body (prior draws on the ``"dataset"`` stage + the SEARCH pipeline
    with scenario effects + the registry truth labels) must
    ``make_jaxpr``/``eval_shape`` and hold a stable jit cache (retrace
    count == 1) over a canonical tiny spec — the dynamic twin of the
    record program's shared-registry single-build contract, run where
    the linter gate runs so a trace-unsafe edit to the sampler, the
    SEARCH scenario hooks, or a registry truth function fails CI before
    it reaches a corpus run.
    """
    import numpy as np

    import jax

    from ..datasets.sampler import RecordSampler
    from ..datasets.spec import canonicalize

    canonical = canonicalize({
        "nchan": 2, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
        "sample_rate_mhz": 0.2048, "tobs_s": 0.02, "period_s": 0.005,
        "smean_jy": 0.05, "seed": 0, "n_records": 8, "dm": 10.0,
        "scenarios": ["scintillation", "rfi", "single_pulse"],
        "priors": {"dm": {"dist": "uniform", "lo": 5.0, "hi": 20.0}},
    })
    sampler = RecordSampler(canonical)
    ctx = sampler._program_context()
    prof = jax.numpy.asarray(sampler._profiles_np)
    freqs = jax.numpy.asarray(
        np.asarray(sampler.cfg.meta.dat_freq_mhz(), np.float32))
    chan_ids = jax.numpy.arange(sampler.cfg.meta.nchan)

    def record(key, idx):
        return ctx._record(key, idx, prof, freqs, chan_ids)

    _check_one("dataset_record", record,
               (jax.random.key(0), jax.numpy.int32(0)))
    return [ProbeResult("dataset_record", "ok")]


def run_trace_check(symbols=None):
    """Probe the given ops symbols (default: all of ``ops.__all__``).

    Returns a list of :class:`ProbeResult`; raises on the first op whose
    trace fails, and on any public symbol with neither a probe nor an
    exemption (coverage is part of the contract).
    """
    from .. import ops

    names = list(ops.__all__) if symbols is None else list(symbols)
    specs = probe_specs()
    missing = [n for n in names if n not in specs and n not in EXEMPT]
    if missing:
        raise AssertionError(
            f"ops symbols with no trace probe and no exemption: {missing} "
            "— add a canonical-shape entry to analysis/trace_check.py")
    results = []
    for name in names:
        if name in EXEMPT:
            results.append(ProbeResult(name, "exempt", EXEMPT[name]))
            continue
        fn, args = specs[name]
        _check_one(name, fn, args)
        results.append(ProbeResult(name, "ok"))
    return results
