"""psrlint checkers: the six rule implementations.

Every checker is a small AST pass over one module
(:class:`~psrsigsim_tpu.analysis.core.ModuleContext`).  They share the
import-alias resolver and the jit-reachability walk below; none of them
imports jax — static claims are cross-checked at trace time by
:mod:`psrsigsim_tpu.analysis.trace_check` instead.

Heuristics are tuned for THIS codebase's idioms (documented per rule in
docs/static_analysis.md):

* branching on ``_is_concrete(x)`` is the sanctioned concrete/traced
  fork — np/scipy work inside the concrete branch is host-side by
  construction and exempt from PSR102;
* ``float(x)`` inside a ``try`` with a handler is the sanctioned
  "is this traced?" probe (ops/stats.py) and exempt from PSR101;
* ``stage_key``/``fold_in`` DERIVE keys and may be applied repeatedly to
  one root; samplers CONSUME keys and may see each key once (PSR103).
"""

from __future__ import annotations

import ast

from .core import Finding, RULES

__all__ = ["default_checkers"]

_JIT_WRAPPERS = {
    "jax.jit", "jit", "pjit", "jax.experimental.pjit.pjit",
    "jax.vmap", "vmap", "jax.pmap", "pmap",
    "shard_map", "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "jax.lax.map", "lax.map",
}
_TRACED_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.random.", "jnp.")
_RNG_DERIVERS = {"split", "fold_in", "stage_key", "next_key", "clone"}
_RNG_NONCONSUMING = {"key", "PRNGKey", "key_data", "wrap_key_data",
                     "key_impl", "unsafe_rbg_key"}
_DTYPE_TOKENS = {
    "dtype", "float16", "bfloat16", "float32", "float64", "int8", "int16",
    "int32", "int64", "uint8", "uint16", "uint32", "uint64", "bool_",
    "complex64", "complex128",
}
_JNP_CONSTRUCTORS = {"array", "asarray", "full", "full_like", "zeros",
                     "ones", "arange", "linspace"}


def _aliases(tree):
    """Map local names to canonical dotted import paths."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    out[a.name.split(".")[0]] = a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Resolver:
    def __init__(self, tree):
        self.aliases = _aliases(tree)

    def resolve(self, node):
        """Canonical dotted path of a Name/Attribute expr, or None."""
        dotted = _dotted(node)
        if dotted is None:
            return None
        first, _, rest = dotted.partition(".")
        base = self.aliases.get(first, first)
        return f"{base}.{rest}" if rest else base

    def call_name(self, call):
        return self.resolve(call.func) if isinstance(call, ast.Call) else None


def _is_jnp(resolved):
    return bool(resolved) and resolved.startswith(_TRACED_PREFIXES)


def _walk_no_nested_defs(node):
    """Walk an AST subtree WITHOUT descending into nested function/class
    scopes (their bodies are visited when that scope is analyzed)."""
    stack = [node]
    first = True
    while stack:
        cur = stack.pop()
        if not first and isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                  ast.Lambda)
        ):
            continue
        first = False
        yield cur
        stack.extend(ast.iter_child_nodes(cur))


# -- jit reachability --------------------------------------------------------

class _FunctionIndex:
    """All function-like scopes in a module + which are jit-reachable."""

    def __init__(self, ctx, res):
        self.funcs = []       # (node, name, parent_chain)
        self.by_name = {}
        self._collect(ctx.tree)
        self.reachable = self._reach(ctx, res)

    def _collect(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs.append(node)
                self.by_name.setdefault(node.name, node)
            elif isinstance(node, ast.Lambda):
                self.funcs.append(node)

    def _roots(self, ctx, res):
        roots = set()
        if ctx.assume_jitted():
            for node in ctx.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    roots.add(node)
        for fn in self.funcs:
            for deco in getattr(fn, "decorator_list", []):
                target = deco.func if isinstance(deco, ast.Call) else deco
                name = res.resolve(target)
                if name in _JIT_WRAPPERS:
                    roots.add(fn)
                elif (isinstance(deco, ast.Call)
                      and name in ("functools.partial", "partial")
                      and deco.args
                      and res.resolve(deco.args[0]) in _JIT_WRAPPERS):
                    roots.add(fn)
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and res.call_name(node) in _JIT_WRAPPERS and node.args):
                arg = node.args[0]
                if isinstance(arg, ast.Lambda):
                    roots.add(arg)
                elif (isinstance(arg, ast.Name)
                      and arg.id in self.by_name):
                    roots.add(self.by_name[arg.id])
        return roots

    def _reach(self, ctx, res):
        reachable = set(self._roots(ctx, res))
        frontier = list(reachable)
        while frontier:
            fn = frontier.pop()
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    callee = None
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Name)):
                        callee = self.by_name.get(node.func.id)
                    if callee is not None and callee not in reachable:
                        reachable.add(callee)
                        frontier.append(callee)
        return reachable


def _resolver_of(ctx):
    """Per-module resolver, built once and shared by every checker."""
    res = ctx.cache.get("resolver")
    if res is None:
        res = ctx.cache["resolver"] = _Resolver(ctx.tree)
    return res


def _index_of(ctx):
    """Per-module function index + jit reachability, built once."""
    idx = ctx.cache.get("func_index")
    if idx is None:
        idx = ctx.cache["func_index"] = _FunctionIndex(ctx, _resolver_of(ctx))
    return idx


def _guarded_of(ctx):
    """Per-module ``_is_concrete``-guarded node ids, built once (used by
    both PSR102 and PSR104)."""
    ids = ctx.cache.get("guarded_ids")
    if ids is None:
        ids = ctx.cache["guarded_ids"] = _concrete_guarded_ids(
            ctx.tree, _resolver_of(ctx))
    return ids


def _func_line(fn):
    return getattr(fn, "lineno", 0)


def _concrete_guarded_ids(root, res):
    """ids of nodes inside ``if _is_concrete(...)`` bodies — the sanctioned
    host/traced fork (ops/shift.py): host numpy/float64 work there runs at
    trace time on concrete values by construction."""
    exempt = set()
    for node in ast.walk(root):
        if not isinstance(node, ast.If):
            continue
        guarded = any(
            isinstance(t, ast.Call)
            and (res.call_name(t) or "").split(".")[-1] == "_is_concrete"
            for t in ast.walk(node.test)
        )
        if guarded:
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    exempt.add(id(sub))
    return exempt


def _body_stmts(fn):
    return fn.body if isinstance(fn.body, list) else [ast.Expr(fn.body)]


# -- PSR101: trace safety ----------------------------------------------------

class TraceSafetyChecker:
    rule = "PSR101"

    def check(self, ctx):
        res = _resolver_of(ctx)
        index = _index_of(ctx)
        severity = RULES[self.rule][0]
        for fn in index.funcs:
            if fn not in index.reachable:
                continue
            yield from self._check_fn(ctx, res, fn, severity)

    def _check_fn(self, ctx, res, fn, severity):
        derived = set()
        in_probe_try = set()
        assigns = []
        for node in _walk_no_nested_defs(fn):
            if isinstance(node, ast.Try) and node.handlers:
                for sub in ast.walk(node):
                    in_probe_try.add(id(sub))
            if isinstance(node, ast.Assign):
                assigns.append(node)
        # taint assignments to a FIXPOINT: the walk order is arbitrary,
        # and `b = a + 1` must become traced whenever `a = jnp.zeros(3)`
        # does, regardless of which assignment is seen first
        changed = True
        while changed:
            changed = False
            for node in assigns:
                if not self._traced(node.value, res, derived):
                    continue
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name) and n.id not in derived:
                            derived.add(n.id)
                            changed = True

        def finding(node, msg):
            return Finding(ctx.rel, node.lineno, node.col_offset, self.rule,
                           msg, severity, func_line=_func_line(fn))

        for node in _walk_no_nested_defs(fn):
            if isinstance(node, (ast.If, ast.While)):
                if self._traced_test(node.test, res, derived):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield finding(
                        node, f"`{kind}` branches on a traced value inside "
                              "jit-reachable code; use jnp.where / "
                              "lax.cond or hoist to a static argument")
            elif isinstance(node, ast.Assert):
                if self._traced_test(node.test, res, derived):
                    yield finding(
                        node, "`assert` on a traced value never runs under "
                              "jit; use checkify or validate statically")
            elif isinstance(node, ast.Call):
                if (isinstance(node.func, ast.Name)
                        and node.func.id in ("float", "int", "bool")
                        and len(node.args) == 1
                        and id(node) not in in_probe_try
                        and self._traced(node.args[0], res, derived)):
                    yield finding(
                        node, f"`{node.func.id}()` forces a traced value "
                              "concrete (ConcretizationTypeError under "
                              "jit / silent host sync otherwise)")
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr == "item"
                      and not node.args
                      and self._traced(node.func.value, res, derived)):
                    yield finding(
                        node, "`.item()` on a traced value forces a host "
                              "round-trip inside jit-reachable code")

    # attribute reads that are STATIC on tracers (shape/dtype metadata)
    _STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type",
                     "sharding", "itemsize"}

    @classmethod
    def _traced_test(cls, expr, res, derived):
        """A branch test containing ``isinstance(...)`` anywhere is the
        static type-dispatch fork — never flagged as a whole."""
        for node in ast.walk(expr):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "isinstance"):
                return False
        return cls._traced(expr, res, derived)

    @classmethod
    def _traced(cls, expr, res, derived):
        """Whether evaluating ``expr`` can touch a traced VALUE.

        Deliberately not flagged: ``x.shape``-style metadata reads (static
        under trace), ``x is None`` identity checks, and any expression
        containing an ``isinstance`` call (the static type-dispatch fork,
        e.g. ops/stats.py's concrete/traced ``off`` split)."""
        if isinstance(expr, ast.Attribute) and expr.attr in cls._STATIC_ATTRS:
            return False
        if isinstance(expr, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops
        ):
            return False
        if isinstance(expr, ast.Call):
            if (isinstance(expr.func, ast.Name)
                    and expr.func.id == "isinstance"):
                return False
            if _is_jnp(res.call_name(expr)):
                return True
        if isinstance(expr, ast.Name):
            return expr.id in derived
        return any(cls._traced(child, res, derived)
                   for child in ast.iter_child_nodes(expr))


# -- PSR102: host numpy/scipy leakage ---------------------------------------

class HostNumpyChecker:
    rule = "PSR102"

    def check(self, ctx):
        if not ctx.in_device_modules():
            return
        res = _resolver_of(ctx)
        index = _index_of(ctx)
        severity = RULES[self.rule][0]
        allow = set(ctx.config.numpy_allow)
        exempt = _guarded_of(ctx)
        for fn in index.funcs:
            if fn not in index.reachable:
                continue
            for node in _walk_no_nested_defs(fn):
                if not isinstance(node, ast.Call) or id(node) in exempt:
                    continue
                name = res.call_name(node)
                if not name or not name.startswith(("numpy.", "scipy.")):
                    continue
                if name.split(".")[-1] in allow:
                    continue
                yield Finding(
                    ctx.rel, node.lineno, node.col_offset, self.rule,
                    f"host call `{name}` inside the jitted pipeline "
                    "forces a host round-trip (use jax.numpy, or move "
                    "to config/staging time)", severity,
                    func_line=_func_line(fn))

# -- PSR103: RNG key discipline ---------------------------------------------

_RANK = {"fresh": 0, "derived": 1, "sunk": 2}


class RngReuseChecker:
    rule = "PSR103"

    def check(self, ctx):
        res = _resolver_of(ctx)
        index = _index_of(ctx)
        severity = RULES[self.rule][0]
        sinks = set(ctx.config.rng_sinks)
        seen = set()
        for fn in index.funcs:
            findings = []
            self._scan_block(_body_stmts(fn), {}, res, sinks, findings,
                             ctx, fn, severity)
            for f in findings:
                key = (f.line, f.col, f.message)
                if key not in seen:
                    seen.add(key)
                    yield f

    # one statement's rng events, in source order, no nested scopes
    def _events(self, stmt, res, sinks):
        events = []
        for node in _walk_no_nested_defs(stmt):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = res.call_name(node)
            if not name:
                continue
            last = name.split(".")[-1]
            arg = node.args[0]
            if not isinstance(arg, ast.Name):
                continue
            if name.startswith("jax.random."):
                if last in _RNG_DERIVERS:
                    events.append(("derive", arg.id, node))
                elif last not in _RNG_NONCONSUMING:
                    events.append(("sink", arg.id, node))
            elif last in _RNG_DERIVERS:
                events.append(("derive", arg.id, node))
            elif last in sinks:
                events.append(("sink", arg.id, node))
        events.sort(key=lambda e: (e[2].lineno, e[2].col_offset))
        return events

    def _apply(self, stmt, state, res, sinks, findings, ctx, fn, severity):
        for kind, key, node in self._events(stmt, res, sinks):
            status = state.get(key)
            if kind == "sink":
                if status in ("derived", "sunk"):
                    how = ("already consumed by a sampler"
                           if status == "sunk"
                           else "already used to derive subkeys")
                    findings.append(Finding(
                        ctx.rel, node.lineno, node.col_offset, self.rule,
                        f"PRNG key `{key}` {how}; pass a fresh "
                        "jax.random.split/fold_in product instead of "
                        "reusing it", severity,
                        func_line=_func_line(fn)))
                state[key] = "sunk"
            else:
                if status == "sunk":
                    findings.append(Finding(
                        ctx.rel, node.lineno, node.col_offset, self.rule,
                        f"PRNG key `{key}` was consumed by a sampler and "
                        "is now re-derived; derive before sampling",
                        severity, func_line=_func_line(fn)))
                elif status != "sunk":
                    state[key] = "derived"
        # plain reassignment of a name resets its key state
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for tgt in targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        state.pop(n.id, None)

    def _merge(self, states):
        live = [s for s in states if s is not None]
        if not live:
            return None
        merged = {}
        for s in live:
            for k, v in s.items():
                if k not in merged or _RANK[v] > _RANK[merged[k]]:
                    merged[k] = v
        return merged

    def _scan_block(self, stmts, state, res, sinks, findings, ctx, fn,
                    severity):
        """Abstract interpretation of one statement list; returns the exit
        state or None when every path terminates (return/raise)."""
        args = (res, sinks, findings, ctx, fn, severity)
        for stmt in stmts:
            if isinstance(stmt, (ast.Return, ast.Raise)):
                self._apply(stmt, state, *args)
                return None
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.If):
                self._apply(ast.Expr(stmt.test), state, *args)
                s1 = self._scan_block(stmt.body, dict(state), *args)
                s2 = self._scan_block(stmt.orelse, dict(state), *args)
                merged = self._merge([s1, s2])
                if merged is None:
                    return None
                state.clear()
                state.update(merged)
            elif isinstance(stmt, (ast.For, ast.While)):
                head = stmt.iter if isinstance(stmt, ast.For) else stmt.test
                self._apply(ast.Expr(head), state, *args)
                if isinstance(stmt, ast.For):
                    for n in ast.walk(stmt.target):
                        if isinstance(n, ast.Name):
                            state.pop(n.id, None)
                # two passes: the second exposes cross-iteration key reuse
                s1 = self._scan_block(list(stmt.body), dict(state), *args)
                if s1 is not None:
                    s2 = self._scan_block(list(stmt.body), dict(s1), *args)
                    merged = self._merge([state, s1, s2])
                    state.clear()
                    state.update(merged)
                s3 = self._scan_block(stmt.orelse, dict(state), *args)
                if s3 is not None:
                    state.update(s3)
            elif isinstance(stmt, ast.Try):
                s1 = self._scan_block(stmt.body, dict(state), *args)
                hs = [self._scan_block(h.body, dict(state), *args)
                      for h in stmt.handlers]
                merged = self._merge([s1] + hs)
                if merged is None and not stmt.finalbody:
                    return None
                state.clear()
                state.update(merged or {})
                sf = self._scan_block(stmt.finalbody, state, *args)
                if sf is None:
                    return None
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._apply(ast.Expr(item.context_expr), state, *args)
                sb = self._scan_block(stmt.body, state, *args)
                if sb is None:
                    return None
            else:
                self._apply(stmt, state, *args)
        return state


# -- PSR104: dtype hygiene ---------------------------------------------------

class DtypeChecker:
    rule = "PSR104"

    def check(self, ctx):
        if not ctx.in_device_modules():
            return
        res = _resolver_of(ctx)
        severity = RULES[self.rule][0]
        exempt = _guarded_of(ctx)
        func_stack = []

        def fline():
            return func_stack[-1] if func_stack else 0

        def visit(node):
            is_fn = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            if is_fn:
                func_stack.append(node.lineno)
            if id(node) not in exempt:
                yield from self._check_node(ctx, res, node, severity, fline())
            for child in ast.iter_child_nodes(node):
                yield from visit(child)
            if is_fn:
                func_stack.pop()

        yield from visit(ctx.tree)

    def _check_node(self, ctx, res, node, severity, func_line):
        if isinstance(node, (ast.Attribute, ast.Name)):
            name = res.resolve(node)
            if name in ("numpy.float64", "jax.numpy.float64",
                        "numpy.float128", "numpy.longdouble"):
                yield Finding(
                    ctx.rel, node.lineno, node.col_offset, self.rule,
                    f"`{name.split('.')[-1]}` in device code breaks "
                    "float32 bit-reproducibility (TPUs emulate f64; "
                    "keep f64 host-side or split hi/lo — ops/dfloat.py)",
                    severity, func_line=func_line)
            return
        if not isinstance(node, ast.Call):
            return
        for kw in node.keywords:
            if kw.arg != "dtype":
                continue
            if isinstance(kw.value, ast.Name) and kw.value.id == "float":
                yield Finding(
                    ctx.rel, node.lineno, node.col_offset, self.rule,
                    "`dtype=float` means float64; name the width "
                    "explicitly (jnp.float32)", severity,
                    func_line=func_line)
            elif (isinstance(kw.value, ast.Constant)
                  and kw.value.value == "float64"):
                yield Finding(
                    ctx.rel, node.lineno, node.col_offset, self.rule,
                    '`dtype="float64"` in device code breaks float32 '
                    "bit-reproducibility", severity, func_line=func_line)
        name = res.call_name(node) or ""
        first, _, last = name.rpartition(".")
        if (first in ("jax.numpy", "jnp") and last in _JNP_CONSTRUCTORS
                and not self._has_dtype(node, res)
                and any(isinstance(a, ast.Constant)
                        and isinstance(a.value, float)
                        for a in node.args)):
            yield Finding(
                ctx.rel, node.lineno, node.col_offset, self.rule,
                f"`{name}` from a bare float literal without an explicit "
                "dtype follows jax_enable_x64 (f32 today, f64 under the "
                "flag); pin dtype= for bit-stable output", severity,
                func_line=func_line)

    @staticmethod
    def _has_dtype(call, res):
        if any(kw.arg == "dtype" for kw in call.keywords):
            return True
        for arg in call.args:
            dotted = _dotted(arg)
            if dotted and dotted.split(".")[-1] in _DTYPE_TOKENS:
                return True
        return False


# -- PSR105: global mutable state ---------------------------------------------

class GlobalStateChecker:
    rule = "PSR105"

    def check(self, ctx):
        severity = RULES[self.rule][0]
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            declared = set()
            for node in _walk_no_nested_defs(fn):
                if isinstance(node, ast.Global):
                    declared.update(node.names)
            if not declared:
                continue
            mutated = set()
            for node in _walk_no_nested_defs(fn):
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    for tgt in targets:
                        if (isinstance(tgt, ast.Name)
                                and tgt.id in declared):
                            mutated.add(tgt.id)
            # `global X` + assignment IS module-global mutation whether or
            # not X also has a module-level initializer
            for name in sorted(mutated):
                yield Finding(
                    ctx.rel, fn.lineno, fn.col_offset, self.rule,
                    f"`{fn.name}` rebinds module-level `{name}`: "
                    "process-global state silently couples independent "
                    "instances (the simulate.py ephemeris bug class); "
                    "prefer instance state or explicit re-application",
                    severity, func_line=_func_line(fn))


# -- PSR106: sharding axis consistency ----------------------------------------

class ShardingAxesChecker:
    rule = "PSR106"

    def check(self, ctx):
        if not ctx.mesh_axes:
            return
        res = _resolver_of(ctx)
        severity = RULES[self.rule][0]
        func_stack = []

        def visit(node):
            is_fn = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            if is_fn:
                func_stack.append(node.lineno)
            yield from self._check_call(ctx, res, node, severity,
                                        func_stack[-1] if func_stack else 0)
            for child in ast.iter_child_nodes(node):
                yield from visit(child)
            if is_fn:
                func_stack.pop()

        yield from visit(ctx.tree)

    def _check_call(self, ctx, res, node, severity, func_line):
        if not isinstance(node, ast.Call):
            return
        name = res.call_name(node) or ""
        last = name.split(".")[-1]
        if last == "Mesh":       # axis-name tuples here are definitions
            return
        if not (last == "PartitionSpec"
                or (isinstance(node.func, ast.Name)
                    and node.func.id == "P")):
            return
        for arg in node.args:
            elems = arg.elts if isinstance(arg, ast.Tuple) else [arg]
            for el in elems:
                if (isinstance(el, ast.Constant)
                        and isinstance(el.value, str)
                        and el.value not in ctx.mesh_axes):
                    yield Finding(
                        ctx.rel, el.lineno, el.col_offset, self.rule,
                        f"sharding axis '{el.value}' is not defined "
                        "by the mesh (known axes: "
                        f"{sorted(ctx.mesh_axes)}); shard_map would "
                        "fail at runtime or silently replicate",
                        severity, func_line=func_line)


def default_checkers():
    return [
        TraceSafetyChecker(),
        HostNumpyChecker(),
        RngReuseChecker(),
        DtypeChecker(),
        GlobalStateChecker(),
        ShardingAxesChecker(),
    ]
