"""psrlint CLI: ``python -m psrsigsim_tpu.analysis [paths...]``.

Exit status is 0 when every finding is covered by the baseline ratchet
(analysis/baseline.txt), 1 when any (rule, file) bucket regressed, and
2 on usage errors.  ``--trace-check`` additionally runs the dynamic
trace probe over the public ops surface (imports jax).
"""

from __future__ import annotations

import argparse
import os
import sys

from .core import (RULES, baseline_regressions, iter_source_files,
                   load_baseline, load_config, run_lint, write_baseline)


def _default_root():
    """The installed package tree — so a bare invocation lints us."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _default_baseline():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.txt")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m psrsigsim_tpu.analysis",
        description="psrlint: JAX/TPU correctness linter "
                    "(trace-safety, RNG discipline, dtype/sharding hygiene)")
    parser.add_argument("paths", nargs="*",
                        help="package roots to lint (default: the "
                             "installed psrsigsim_tpu tree)")
    parser.add_argument("--baseline", default=None,
                        help="baseline ratchet file (default: the "
                             "packaged analysis/baseline.txt)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding as a failure")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "(ratchet down after fixing debt)")
    parser.add_argument("--trace-check", action="store_true",
                        help="also run the dynamic trace probe over "
                             "psrsigsim_tpu.ops (imports jax)")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="print only regressions, not baselined debt")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, (severity, desc) in sorted(RULES.items()):
            print(f"{rule} [{severity}] {desc}")
        return 0

    roots = args.paths or [_default_root()]
    findings = []
    scanned = set()       # rel paths (baseline keys are rel)
    scanned_abs = set()   # dedup identity is the FILE, not its rel path
    for root in roots:
        if not os.path.exists(root):
            print(f"error: no such path: {root}", file=sys.stderr)
            return 2
        config = load_config(root)
        # overlapping roots must not lint a file twice (doubled findings
        # read as phantom baseline regressions, and re-parsing is wasted
        # work) — dedup keys on the absolute path: two DIFFERENT packages
        # may both own a core.py, and the second one must still be gated
        pairs = list(iter_source_files(root, config))
        fresh = [(path, rel) for path, rel in pairs
                 if path not in scanned_abs]
        scanned_abs |= {path for path, _ in pairs}
        scanned |= {rel for _, rel in pairs}
        findings.extend(run_lint(root, config=config, files=fresh))

    baseline_path = args.baseline or _default_baseline()
    if args.write_baseline:
        # a sub-path scan re-ratchets only what it linted: entries for
        # files outside the scanned scope are preserved, not discarded
        preserve = {k: v for k, v in load_baseline(baseline_path).items()
                    if k[1] not in scanned}
        write_baseline(baseline_path, findings, preserve=preserve)
        print(f"wrote {len(findings)} findings "
              f"(+{len(preserve)} out-of-scope entries preserved) to "
              f"{baseline_path}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    regressions = baseline_regressions(findings, baseline)
    reg_keys = {(f.rule, f.path) for f in regressions}

    shown = 0
    for f in findings:
        is_reg = (f.rule, f.path) in reg_keys
        if args.quiet and not is_reg:
            continue
        tag = "" if is_reg else "  (baselined)"
        print(f.format() + tag)
        shown += 1

    status = 0
    if regressions:
        print(f"\npsrlint: {len(regressions)} finding(s) above baseline "
              f"in {len(reg_keys)} (rule, file) bucket(s) — fix them or "
              "consciously ratchet with --write-baseline", file=sys.stderr)
        status = 1
    elif shown:
        print(f"\npsrlint: {shown} baselined finding(s), no regressions")
    else:
        print("psrlint: clean")

    if args.trace_check:
        from .trace_check import (run_dataset_trace_check,
                                  run_serve_trace_check, run_trace_check)

        results = run_trace_check()
        ok = sum(1 for r in results if r.status == "ok")
        exempt = sum(1 for r in results if r.status == "exempt")
        serve_ok = len(run_serve_trace_check())
        dataset_ok = len(run_dataset_trace_check())
        print(f"trace-check: {ok} ops traced clean, {exempt} exempt, "
              f"{serve_ok} serving bucket program(s) and "
              f"{dataset_ok} dataset record program(s) traced clean")

    return status


if __name__ == "__main__":
    sys.exit(main())
