"""psrlint core: findings, configuration, suppression, baseline, driver.

The checkers themselves live in :mod:`psrsigsim_tpu.analysis.checkers`;
this module is pure stdlib (no jax import) so ``python -m
psrsigsim_tpu.analysis`` starts instantly and runs anywhere — the dynamic
trace probe (:mod:`psrsigsim_tpu.analysis.trace_check`) is the only part
that touches a JAX backend.

Design notes
------------
* A :class:`Finding` is one (path, line, rule) diagnostic with a stable
  rule ID (``PSR1xx``).  Output format is the classic
  ``path:line:col: RULE [severity] message``.
* Suppression is source-level: ``# psrlint: disable=PSR102`` on a line
  silences that line; the same comment on a ``def`` line silences the
  whole function body (checkers attach the owning function's line to
  each finding for exactly this purpose).
* The baseline file is a RATCHET, not an allowlist of lines: it records
  per ``(rule, file)`` finding COUNTS, so pre-existing debt neither
  blocks CI nor shields new regressions in other files, and shrinking a
  count can be locked in with ``--write-baseline``.  Line-based
  baselines rot on every unrelated edit; count ratchets do not.
"""

from __future__ import annotations

import ast
import fnmatch
import os
import re
from dataclasses import dataclass, field, replace

__all__ = [
    "Finding",
    "LintConfig",
    "load_config",
    "run_lint",
    "load_baseline",
    "write_baseline",
    "baseline_regressions",
    "iter_source_files",
    "RULES",
]

# rule ID -> (severity, one-line description); the registry the CLI and
# docs/static_analysis.md both mirror.  Checkers are registered against
# these IDs in checkers.py.
RULES = {
    "PSR100": ("error", "source file does not parse"),
    "PSR101": ("error", "trace-unsafe Python control flow / coercion on a "
                        "traced value in jit-reachable code"),
    "PSR102": ("warning", "host numpy/scipy call inside the jitted "
                          "pipeline (forces a host round-trip)"),
    "PSR103": ("error", "PRNG key passed to two sinks without an "
                        "intervening split/fold_in"),
    "PSR104": ("warning", "float64/implicit dtype in device code "
                          "(bit-reproducibility hazard)"),
    "PSR105": ("warning", "module-level mutable state rebound from a "
                          "function body"),
    "PSR106": ("error", "sharding axis name not defined by the mesh"),
}


@dataclass(frozen=True)
class Finding:
    """One diagnostic, ordered for stable output."""

    path: str        # posix relpath from the scan root
    line: int
    col: int
    rule: str
    message: str
    severity: str = "warning"
    func_line: int = 0   # def-line of the owning function (0 = module)

    def format(self):
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.severity}] {self.message}")

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)


@dataclass
class LintConfig:
    """Checker scoping knobs; defaults MIRROR ``[tool.psrlint]`` in
    pyproject.toml (which overrides them when found) — an installed
    package has no pyproject on its ancestor chain, and the gate must
    behave identically there."""

    include: tuple = ("*.py",)
    exclude: tuple = ("analysis/*", "data/*", "io/native/*")
    # globs (relative to the scan root) of modules whose functions feed
    # jitted pipelines: PSR102/PSR104 only fire inside these
    device_modules: tuple = ("ops/*", "parallel/*", "models/*",
                             "simulate/pipeline.py")
    # every top-level function in these globs is treated as jit-reachable
    # even without a local @jit site (ops are the pipeline's kernels)
    assume_jitted: tuple = ("ops/*",)
    # np.<attr> accesses that never force a host round-trip on tracers
    numpy_allow: tuple = ("ndim", "shape", "size", "iinfo", "finfo",
                          "dtype", "result_type", "promote_types")
    # local wrappers that CONSUME a PRNG key like a jax.random sampler
    rng_sinks: tuple = ("chi2_sample", "normal_sample", "blocked_chan_chi2",
                        "blocked_chan_normal", "chan_chi2_field",
                        "chan_normal_field", "flat_normal_field",
                        "flat_chi2_field", "hw_chan_field")
    # axis names beyond those discovered in parallel/mesh.py (the seq
    # pipeline defines its own 1-D mesh in parallel/seqshard.py)
    mesh_axes_extra: tuple = ("seq",)
    # explicit axis set: overrides discovery entirely (used by fixtures)
    mesh_axes: tuple = ()
    baseline: str = ""   # resolved by the CLI; empty = packaged default


_LIST_RE = re.compile(r"^\s*([A-Za-z0-9_-]+)\s*=\s*\[(.*)\]\s*$")
_SCALAR_RE = re.compile(r"^\s*([A-Za-z0-9_-]+)\s*=\s*(.+?)\s*$")


def _parse_toml_section(text, section):
    """Minimal TOML reader for one flat section (python 3.10 has no
    tomllib and this container must not grow dependencies): supports
    ``key = "str"`` and string arrays — single-line or spread across
    lines, as TOML formatters emit them."""
    out = {}
    in_section = False
    pending_key = None   # multi-line array being accumulated
    pending_buf = ""
    for raw in text.splitlines():
        line = raw.split("#", 1)[0] if not raw.lstrip().startswith("#") else ""
        if pending_key is not None:
            pending_buf += " " + line.strip()
            if "]" in line:
                out[pending_key] = re.findall(r'"([^"]*)"', pending_buf)
                pending_key = None
            continue
        if not line.strip():
            continue
        if line.strip().startswith("["):
            in_section = line.strip() == f"[{section}]"
            continue
        if not in_section:
            continue
        m = _LIST_RE.match(line)
        if m:
            out[m.group(1)] = re.findall(r'"([^"]*)"', m.group(2))
            continue
        m = re.match(r"^\s*([A-Za-z0-9_-]+)\s*=\s*\[(.*)$", line)
        if m:   # array opened but not closed on this line
            pending_key, pending_buf = m.group(1), m.group(2)
            continue
        m = _SCALAR_RE.match(line)
        if m:
            val = m.group(2).strip().strip('"')
            out[m.group(1)] = val
    return out


def load_config(start_dir):
    """Build a :class:`LintConfig` from the nearest pyproject.toml above
    ``start_dir`` (missing file or section -> defaults)."""
    cfg = LintConfig()
    d = os.path.abspath(start_dir)
    while True:
        pp = os.path.join(d, "pyproject.toml")
        if os.path.isfile(pp):
            with open(pp, encoding="utf-8") as f:
                raw = _parse_toml_section(f.read(), "tool.psrlint")
            mapping = {
                "include": "include", "exclude": "exclude",
                "device-modules": "device_modules",
                "assume-jitted": "assume_jitted",
                "numpy-allow": "numpy_allow",
                "rng-sinks": "rng_sinks",
                "extra-mesh-axes": "mesh_axes_extra",
                "mesh-axes": "mesh_axes",
                "baseline": "baseline",
            }
            kw = {}
            for key, attr in mapping.items():
                if key in raw:
                    val = raw[key]
                    if attr != "baseline" and isinstance(val, str):
                        val = [val]   # every other knob is list-typed
                    kw[attr] = tuple(val) if isinstance(val, list) else val
            cfg = replace(cfg, **kw)
            break
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return cfg


def _matches(rel, patterns):
    return any(fnmatch.fnmatch(rel, pat) for pat in patterns)


def _package_anchor(root):
    """The directory rel paths are measured from: the TOPMOST package
    directory on ``root``'s ancestor chain (so ``psrsigsim_tpu/models``
    and ``psrsigsim_tpu/io/ephem.py`` lint with the same rel paths —
    ``models/...``, ``io/ephem.py`` — as a whole-package scan, keeping
    the device-module globs and baseline keys stable no matter which
    sub-path the CLI is pointed at).  A tree with no ``__init__.py``
    (fixture dirs) anchors at ``root`` itself."""
    d = root if os.path.isdir(root) else os.path.dirname(root)
    anchor = d
    while os.path.isfile(os.path.join(d, "__init__.py")):
        anchor = d
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return anchor


def iter_source_files(root, config):
    """Yield (abspath, posix relpath) of lintable files under ``root``
    (a directory, or a single file).  Rel paths are anchored at the
    enclosing package root, not at ``root`` — see :func:`_package_anchor`."""
    root = os.path.abspath(root)
    anchor = _package_anchor(root)
    if os.path.isfile(root):
        rel = os.path.relpath(root, anchor).replace(os.sep, "/")
        # the single-file form honors the same include/exclude globs as
        # the directory walk — an excluded file must not lint (or
        # ratchet) through the side door
        if _matches(rel, config.include) and not _matches(rel,
                                                          config.exclude):
            yield root, rel
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, anchor).replace(os.sep, "/")
            if not _matches(rel, config.include):
                continue
            if _matches(rel, config.exclude):
                continue
            yield path, rel


# -- suppression -------------------------------------------------------------

_DISABLE_RE = re.compile(r"#\s*psrlint:\s*disable=([A-Z0-9, ]+)")
_DISABLE_FILE_RE = re.compile(r"#\s*psrlint:\s*disable-file=([A-Z0-9, ]+)")


def _suppressions(src):
    """Per-line and per-file rule suppressions from magic comments."""
    by_line = {}
    whole_file = set()
    for i, line in enumerate(src.splitlines(), start=1):
        m = _DISABLE_RE.search(line)
        if m:
            by_line[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
        m = _DISABLE_FILE_RE.search(line)
        if m:
            whole_file |= {r.strip() for r in m.group(1).split(",")
                           if r.strip()}
    return by_line, whole_file


def _suppressed(finding, by_line, whole_file):
    if finding.rule in whole_file:
        return True
    for line in (finding.line, finding.func_line):
        rules = by_line.get(line)
        if rules and (finding.rule in rules or "ALL" in rules):
            return True
    return False


# -- mesh axis discovery -----------------------------------------------------

def discover_mesh_axes(root, config):
    """Axis names the mesh defines: string constants assigned to
    ``*_AXIS`` names at module level of ``parallel/mesh.py`` (the single
    source of truth for the 2-D ensemble mesh), plus config extras."""
    if config.mesh_axes:
        return set(config.mesh_axes) | set(config.mesh_axes_extra)
    axes = set(config.mesh_axes_extra)
    mesh_py = os.path.join(_package_anchor(os.path.abspath(root)),
                           "parallel", "mesh.py")
    if os.path.isfile(mesh_py):
        with open(mesh_py, encoding="utf-8") as f:
            try:
                tree = ast.parse(f.read())
            except SyntaxError:
                return axes
        for node in tree.body:
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id.endswith("_AXIS")
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                axes.add(node.value.value)
    return axes


# -- baseline ratchet --------------------------------------------------------

def load_baseline(path):
    """Read ``rule<TAB>path<TAB>count`` lines -> {(rule, path): count}."""
    counts = {}
    if not path or not os.path.isfile(path):
        return counts
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split("\t")
            if len(parts) != 3:
                continue
            try:
                counts[(parts[0], parts[1])] = int(parts[2])
            except ValueError:   # hand-edited/merge-conflicted count
                continue
    return counts


def write_baseline(path, findings, preserve=None):
    """Write the ratchet file from ``findings``.

    ``preserve``: entries from a previous baseline to carry over
    verbatim — the CLI passes every entry for files OUTSIDE the scanned
    scope, so ``--write-baseline`` on a sub-path re-ratchets only what
    was actually linted instead of silently discarding the rest."""
    counts = dict(preserve or {})
    for f in findings:
        counts[(f.rule, f.path)] = counts.get((f.rule, f.path), 0) + 1
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("# psrlint baseline: rule<TAB>file<TAB>count ratchet.\n"
                 "# Regenerate with: python -m psrsigsim_tpu.analysis "
                 "--write-baseline\n")
        for (rule, rel), n in sorted(counts.items()):
            fh.write(f"{rule}\t{rel}\t{n}\n")


def baseline_regressions(findings, baseline):
    """Findings in (rule, file) buckets whose count EXCEEDS the baseline.

    The whole bucket is reported when it regresses — a count ratchet
    cannot tell old findings from new, and showing every candidate beats
    guessing wrong."""
    buckets = {}
    for f in findings:
        buckets.setdefault((f.rule, f.path), []).append(f)
    regressions = []
    for key, items in sorted(buckets.items()):
        if len(items) > baseline.get(key, 0):
            regressions.extend(items)
    return regressions


# -- driver ------------------------------------------------------------------

@dataclass
class ModuleContext:
    """Everything a checker may need about one source file."""

    path: str          # absolute
    rel: str           # posix relpath from scan root
    src: str
    tree: ast.AST
    config: LintConfig
    mesh_axes: set = field(default_factory=set)
    # per-module scratch shared across checkers (resolver, reachability —
    # built once, read six times)
    cache: dict = field(default_factory=dict)

    def in_device_modules(self):
        return _matches(self.rel, self.config.device_modules)

    def assume_jitted(self):
        return _matches(self.rel, self.config.assume_jitted)


def run_lint(root, config=None, checkers=None, files=None):
    """Lint every source file under ``root``; returns sorted findings
    (suppressions applied, baseline NOT applied — the caller compares).

    ``files``: optional pre-computed ``(abspath, rel)`` pairs to lint
    instead of walking ``root`` — the CLI passes only the not-yet-seen
    files of each root so overlapping roots don't pay a double parse."""
    from .checkers import default_checkers

    config = config if config is not None else load_config(root)
    checkers = default_checkers() if checkers is None else checkers
    mesh_axes = discover_mesh_axes(root, config)
    findings = []
    pairs = iter_source_files(root, config) if files is None else files
    for path, rel in pairs:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as err:
            findings.append(Finding(rel, err.lineno or 1, 0, "PSR100",
                                    f"syntax error: {err.msg}", "error"))
            continue
        ctx = ModuleContext(path=path, rel=rel, src=src, tree=tree,
                            config=config, mesh_axes=mesh_axes)
        by_line, whole_file = _suppressions(src)
        for checker in checkers:
            for finding in checker.check(ctx):
                if not _suppressed(finding, by_line, whole_file):
                    findings.append(finding)
    return sorted(findings, key=Finding.sort_key)
