"""Packaged data assets.

Mirrors the reference's shipped data (reference: psrsigsim/data/ packaged
via setup.py:49): the measured J1713+0747 L-band template profile, the
NANOGrav 11-yr par file for the same pulsar, and the PTA per-pulsar noise
table (reference: psrsigsim/PTA_pulsar_nb_data.txt). All are MIT-licensed
observational data products from the upstream project.

Use :func:`data_path` to locate an asset on disk::

    from psrsigsim_tpu.data import data_path
    prof = np.load(data_path("J1713+0747_profile.npy"))
"""

import os

_DIR = os.path.dirname(os.path.abspath(__file__))

__all__ = ["data_path", "list_data"]


def data_path(name):
    """Absolute path of a packaged data asset; raises if it doesn't exist."""
    p = os.path.join(_DIR, name)
    if not os.path.exists(p):
        raise FileNotFoundError(
            f"no packaged data asset {name!r}; available: {list_data()}"
        )
    return p


def list_data():
    """Names of every packaged data asset."""
    return sorted(
        f for f in os.listdir(_DIR)
        if not f.endswith(".py") and not f.startswith("__")
    )
