"""Telescope observation models (reference layer: psrsigsim/telescope/)."""

from .backend import Backend
from .receiver import Receiver, response_from_data
from .telescope import Arecibo, GBT, Telescope

__all__ = ["Telescope", "Receiver", "response_from_data", "Backend", "GBT", "Arecibo"]
