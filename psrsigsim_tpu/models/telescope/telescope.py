"""Telescope: (receiver, backend) systems; observation = resample +
radiometer noise + clip/quantize.

Behavioral counterpart of psrsigsim/telescope/telescope.py, including the
reference's deliberate quirk that the resampled product is NOT written back
to the signal (DIVERGENCES.md #7) — noise is added at the native rate and the
resampled array is returned only on request.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.resample import block_downsample, rebin
from ...utils.constants import KB_JY_M2_PER_K
from ...utils.quantity import Quantity, make_quant
from .backend import Backend
from .receiver import Receiver

__all__ = ["Telescope", "GBT", "Arecibo"]

_kB = Quantity(KB_JY_M2_PER_K, "Jy*m^2/K")


@jax.jit
def _clip_upper(data, clip):
    # intensity signals clip only from above (reference: telescope.py:141-144);
    # amplitude signals would clip symmetrically, but observe() raises for
    # RF/Baseband before reaching the clip, upstream and here
    return jnp.minimum(data, clip)


class Telescope:
    """A telescope: aperture/area/Tsys + named (receiver, backend) systems
    (reference: telescope.py:14-70)."""

    def __init__(self, aperture, area=None, Tsys=None, name=None):
        self._name = name
        self._aperture = make_quant(aperture, "m")
        self._systems = {}

        if area is None:
            self._area = np.pi * (self.aperture / 2) ** 2
        else:
            self._area = make_quant(area, "m^2")
        self._gain = self.area / (2 * _kB)  # 2 polarizations

        self._Tsys = make_quant(Tsys, "K") if Tsys is not None else None

    def __repr__(self):
        return "Telescope({:s}, {:f}m)".format(self._name, self._aperture.value)

    @property
    def name(self):
        return self._name

    @property
    def area(self):
        return self._area

    @property
    def gain(self):
        return self._gain

    @property
    def aperture(self):
        return self._aperture

    @property
    def systems(self):
        return self._systems

    @property
    def Tsys(self):
        return self._Tsys

    def add_system(self, name=None, receiver=None, backend=None):
        """Append a new (receiver, backend) system
        (reference: telescope.py:67-70)."""
        self._systems[name] = (receiver, backend)

    def observe(self, signal, pulsar, system=None, noise=False,
                ret_resampsig=False):
        """Observe a signal: resample to the backend rate, optionally add
        radiometer noise (in place, native rate), clip and cast
        (reference: telescope.py:72-149).

        Returns the resampled array only if ``ret_resampsig`` (the signal's
        own data is NOT resampled — reference parity, DIVERGENCES.md #7).
        """
        if signal.sigtype in ["RFSignal", "BasebandSignal"]:
            raise NotImplementedError

        rcvr, bak = self.systems[system]

        dt_tel = (1 / (2 * bak.samprate)).to("s").value
        if signal.sigtype == "FilterBankSignal" and signal.sublen is not None:
            dt_sig = (signal.sublen / (signal.nsamp / signal.nsub)).to("s").value
        else:
            dt_sig = (signal.tobs / signal.nsamp).to("s").value

        rate_msg = "sig samp freq = {0:.3f} kHz\ntel samp freq = {1:.3f} kHz".format(
            1e-3 / dt_sig, 1e-3 / dt_tel
        )
        if dt_sig != dt_tel and (dt_tel % dt_sig == 0 or dt_tel > dt_sig):
            print(rate_msg)

        # resample from the PRE-noise buffer, as the reference does
        # (telescope.py:93-127 builds `out` before the noise block); skipped
        # entirely when the caller discards it — the reference computes and
        # throws it away (DIVERGENCES.md #7)
        out = None
        if ret_resampsig:
            sig_in = signal.data
            if dt_sig == dt_tel:
                out = sig_in
            elif dt_tel % dt_sig == 0:
                out = block_downsample(sig_in, int(dt_tel // dt_sig))
            elif dt_tel > dt_sig:
                new_nt = int(float(signal.tobs.to("s").value) // dt_tel)
                out = rebin(sig_in, new_nt)
            else:
                # sub-rate signal: pass through (reference: telescope.py:123-126)
                out = sig_in

        if noise:
            # in-place on the signal at its native rate (reference quirk,
            # DIVERGENCES.md #7)
            rcvr.radiometer_noise(signal, pulsar, gain=self.gain, Tsys=self.Tsys)

        if ret_resampsig:
            out = _clip_upper(out, jnp.float32(signal._draw_max))
            return np.asarray(out).astype(signal.dtype)

    def apply_response(self, signal):
        raise NotImplementedError()

    def rfi(self):
        raise NotImplementedError()

    def init_signal(self, system):
        raise NotImplementedError()


def GBT():
    """The 100m Green Bank Telescope with its NANOGrav-era systems
    (reference: telescope.py:186-206)."""
    g = Telescope(100.0, area=5500.0, Tsys=35.0, name="GBT")
    g.add_system(
        name="820_GUPPI",
        receiver=Receiver(fcent=820, bandwidth=180, name="820"),
        backend=Backend(samprate=3.125, name="GUPPI"),
    )
    g.add_system(
        name="Lband_GUPPI",
        receiver=Receiver(fcent=1400, bandwidth=800, name="Lband"),
        backend=Backend(samprate=12.5, name="GUPPI"),
    )
    g.add_system(
        name="800_GASP",
        receiver=Receiver(fcent=844, bandwidth=64, name="800"),
        backend=Backend(samprate=0.25, name="GASP"),
    )
    g.add_system(
        name="Lband_GASP",
        receiver=Receiver(fcent=1410, bandwidth=64, name="Lband"),
        backend=Backend(samprate=0.25, name="GASP"),
    )
    return g


def Arecibo():
    """The Arecibo 300m telescope with its NANOGrav-era systems
    (reference: telescope.py:209-239)."""
    a = Telescope(300.0, area=22000.0, Tsys=35.0, name="Arecibo")
    a.add_system(
        name="430_PUPPI",
        receiver=Receiver(fcent=430, bandwidth=100, name="430"),
        backend=Backend(samprate=1.5625, name="PUPPI"),
    )
    a.add_system(
        name="Lband_PUPPI",
        receiver=Receiver(fcent=1410, bandwidth=800, name="Lband"),
        backend=Backend(samprate=12.5, name="PUPPI"),
    )
    a.add_system(
        name="Sband_PUPPI",
        receiver=Receiver(fcent=2030, bandwidth=400, name="Sband"),
        backend=Backend(samprate=12.5, name="PUPPI"),
    )
    a.add_system(
        name="327_ASP",
        receiver=Receiver(fcent=327, bandwidth=64, name="327"),
        backend=Backend(samprate=0.25, name="ASP"),
    )
    a.add_system(
        name="430_ASP",
        receiver=Receiver(fcent=432, bandwidth=64, name="430"),
        backend=Backend(samprate=0.25, name="ASP"),
    )
    a.add_system(
        name="Lband_ASP",
        receiver=Receiver(fcent=1412, bandwidth=64, name="Lband"),
        backend=Backend(samprate=0.25, name="ASP"),
    )
    a.add_system(
        name="Sband_ASP",
        receiver=Receiver(fcent=2348, bandwidth=64, name="Sband"),
        backend=Backend(samprate=0.25, name="ASP"),
    )
    return a
