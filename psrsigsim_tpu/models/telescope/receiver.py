"""Telescope receiver: bandpass + radiometer noise.

Behavioral counterpart of psrsigsim/telescope/receiver.py.  Noise levels
follow Lorimer & Kramer eq 7.12 with the Lam et al. 2018a profile-
normalization scaling; the scipy global-RNG draws over ``(Nchan, Nsamp)``
(receiver.py:136,170) become one jitted explicit-key device sample.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.stats import chi2_sample, normal_sample
from ...utils.quantity import make_quant
from ...utils.rng import KeySequence, default_keys

__all__ = ["Receiver", "response_from_data"]


@partial(jax.jit, static_argnames=("df",))
def _add_pow_noise_kernel(key, data, df, norm):
    # df STATIC so chi2_sample's by-value routing (exact gamma for small
    # df, WH for large) applies — a traced df would silently force WH
    return data + chi2_sample(key, df, data.shape) * norm


@jax.jit
def _add_amp_noise_kernel(key, data, norm):
    return data + normal_sample(key, data.shape) * norm


class Receiver:
    """A receiver: flat bandpass (fcent/bandwidth) + receiver temperature
    (reference: receiver.py:12-57).

    Required: EITHER a callable ``response`` carrying ``fcent``/
    ``bandwidth`` attributes in MHz (build one with
    :func:`response_from_data`; the reference stubs this path,
    receiver.py:49) OR ``fcent`` and ``bandwidth`` for a flat response.
    """

    def __init__(self, response=None, fcent=None, bandwidth=None, Trec=35,
                 name=None, seed=None):
        if response is None:
            if fcent is None or bandwidth is None:
                raise ValueError("specify EITHER response OR fcent and bandwidth")
            self._response = _flat_response(fcent, bandwidth)
        else:
            if fcent is not None or bandwidth is not None:
                raise ValueError("specify EITHER response OR fcent and bandwidth")
            # custom bandpass (NotImplemented upstream, receiver.py:49):
            # the callable must carry its band metadata — use
            # response_from_data to build one from sampled data
            fcent = getattr(response, "fcent", None)
            bandwidth = getattr(response, "bandwidth", None)
            if fcent is None or bandwidth is None:
                raise ValueError(
                    "a custom response callable must carry fcent/bandwidth "
                    "attributes (MHz); build it with response_from_data")
            self._response = response

        self._Trec = make_quant(Trec, "K")
        self._name = name
        self._fcent = make_quant(fcent, "MHz")
        self._bandwidth = make_quant(bandwidth, "MHz")
        self._keys = KeySequence(seed) if seed is not None else default_keys

    def __repr__(self):
        return "Receiver({:s})".format(self._name)

    @property
    def name(self):
        return self._name

    @property
    def Trec(self):
        return self._Trec

    @property
    def response(self):
        return self._response

    @property
    def fcent(self):
        return self._fcent

    @property
    def bandwidth(self):
        return self._bandwidth

    def _resolve_tsys(self, Tsys, Tenv):
        """Tsys = Tenv + Trec, unless Tsys given (just Trec if neither)
        (reference: receiver.py:100-108)."""
        tsys_val = Tsys.value if hasattr(Tsys, "value") else Tsys
        tenv_val = Tenv.value if hasattr(Tenv, "value") else Tenv
        if tsys_val is None and tenv_val is None:
            return self.Trec
        if tenv_val is not None:
            if tsys_val is not None:
                raise ValueError("specify EITHER Tsys OR Tenv, not both")
            return make_quant(Tenv, "K") + self.Trec
        return make_quant(Tsys, "K")

    def radiometer_noise(self, signal, pulsar, gain=1, Tsys=None, Tenv=None):
        """Add radiometer noise to the signal in place
        (reference: receiver.py:82-121)."""
        Tsys = self._resolve_tsys(Tsys, Tenv)
        gain = make_quant(gain, "K/Jy")

        if signal.sigtype in ["RFSignal", "BasebandSignal"]:
            self._add_amp_noise(signal, Tsys, gain, pulsar)
        elif signal.sigtype == "FilterBankSignal":
            self._add_pow_noise(signal, Tsys, gain, pulsar)
        else:
            raise NotImplementedError(
                "no pulse method for signal: {}".format(signal.sigtype)
            )

    def _amp_noise_norm(self, signal, Tsys, gain, pulsar):
        """Amplitude-signal noise scale (reference: receiver.py:123-138).

        Reproduces the reference numerically, including its unit quirk:
        U_scale = 1/(sum(max_profile)/samprate) carries a stray MHz that
        ``.value`` silently drops (receiver.py:133-138).
        """
        dt = 1 / signal.samprate
        sigS = Tsys / gain / np.sqrt(2 * dt * signal.bw)
        u_scale = float(signal.samprate.to("MHz").value) / float(
            np.sum(pulsar.Profiles._max_profile)
        )
        return float(
            np.sqrt(float((sigS / signal._Smax).decompose())) * u_scale
        )

    def _pow_noise_norm(self, signal, Tsys, gain, pulsar):
        """Intensity-signal noise scale (reference: receiver.py:140-172)."""
        nbins = signal.nsamp / signal.nsub  # bins per subint
        dt = signal.sublen / nbins
        bw_per_chan = signal.bw / signal.Nchan
        sigS = Tsys / gain / np.sqrt(2 * dt * bw_per_chan)
        df = signal.Nfold if signal.fold else 1
        u_scale = 1.0 / (float(np.sum(pulsar.Profiles._max_profile)) / nbins)
        norm = (
            float(((sigS * signal._draw_norm) / signal._Smax).decompose()) * u_scale
        )
        return norm, float(df)

    def _add_amp_noise(self, signal, Tsys, gain, pulsar):
        norm = self._amp_noise_norm(signal, Tsys, gain, pulsar)
        signal.data = _add_amp_noise_kernel(
            self._keys.next("noise"), signal.data, jnp.float32(norm)
        )

    def _add_pow_noise(self, signal, Tsys, gain, pulsar):
        norm, df = self._pow_noise_norm(signal, Tsys, gain, pulsar)
        signal.data = _add_pow_noise_kernel(
            self._keys.next("noise"), signal.data, float(df),
            jnp.float32(norm)
        )


def response_from_data(fs, values):
    """Generate a callable bandpass from sampled (frequency, response)
    data (stub in the reference, receiver.py:176-180; completed here).

    ``fs`` are frequencies in MHz (monotonically increasing), ``values``
    the measured response at those frequencies.  Returns a callable
    ``response(f)`` interpolating linearly inside the sampled band and
    zero outside it, carrying ``fcent``/``bandwidth`` attributes (the
    response-weighted band center and the sampled span) so
    :class:`Receiver` can take it directly in place of a flat band.
    """
    fs = np.asarray(fs, dtype=np.float64)
    values = np.asarray(values, dtype=np.float64)
    if fs.ndim != 1 or fs.shape != values.shape or fs.size < 2:
        raise ValueError("fs and values must be matching 1-D arrays "
                         "with at least two samples")
    if np.any(np.diff(fs) <= 0):
        raise ValueError("fs must be strictly increasing")

    def response(f):
        # .to("MHz") BEFORE .value: make_quant returns compatible
        # quantities unchanged, so a GHz input must be converted, not
        # stripped (same handling as _flat_response below)
        fq = np.asarray(make_quant(f, "MHz").to("MHz").value,
                        dtype=np.float64)
        return np.interp(fq, fs, values, left=0.0, right=0.0)

    # fcent/bandwidth describe the SAMPLED band: the midpoint pairs with
    # the span so [fcent - bw/2, fcent + bw/2] is exactly [fs[0], fs[-1]]
    # (a response-weighted centroid would shift the implied band off the
    # sampled one for asymmetric responses)
    response.fcent = float(0.5 * (fs[0] + fs[-1]))
    response.bandwidth = float(fs[-1] - fs[0])
    return response


def _flat_response(fcent, bandwidth):
    """Flat (heaviside-edged) bandpass callable
    (reference: receiver.py:182-197)."""
    fc = make_quant(fcent, "MHz")
    bw = make_quant(bandwidth, "MHz")
    fmin = fc - bw / 2
    fmax = fc + bw / 2

    def bandpass(f):
        f = make_quant(f, "MHz")
        return np.heaviside((f - fmin).to("MHz").value, 0) * np.heaviside(
            (fmax - f).to("MHz").value, 0
        )

    return bandpass
