"""Telescope backend: sampler metadata, ADC, folding
(behavioral counterpart of psrsigsim/telescope/backend.py)."""

from __future__ import annotations

import numpy as np

from ...ops.window import fold_periods
from ...utils.quantity import make_quant

__all__ = ["Backend"]


class Backend:
    """Backend sampler (reference: backend.py:10-31)."""

    def __init__(self, samprate=None, name=None):
        self._name = name
        self._samprate = make_quant(samprate, "MHz")

    def __repr__(self):
        return "Backend({:s})".format(self._name)

    @property
    def name(self):
        return self._name

    @property
    def samprate(self):
        return self._samprate

    def adc(self, signal):
        """analog-digital-converter (no-op upstream, backend.py:27-31;
        kept as a no-op for parity — int8 quantization happens in
        ``Telescope.observe``)."""

    def fold(self, signal, pulsar):
        """Fold data at the pulsar period: sum complete periods into one
        profile per channel.

        The reference's reshape (backend.py:34-49) only succeeds for one
        special observation length; we implement the evident intent
        (DIVERGENCES.md #2): ``(Nf, Nt) -> (Nf, Nph)`` with
        ``Nph = int(period * samprate)``, ragged tail truncated.
        """
        nph = int((pulsar.period * signal.samprate).decompose())
        return fold_periods(signal.data, nph)
