"""1-D pulse profile conveniences over the portrait classes
(behavioral counterpart of psrsigsim/pulsar/profiles.py)."""

from __future__ import annotations

import numpy as np

from .portraits import DataPortrait, GaussPortrait, PulsePortrait

__all__ = ["PulseProfile", "GaussProfile", "UserProfile", "DataProfile"]


class PulseProfile(PulsePortrait):
    """Base class for 1-D pulse profiles (reference: profiles.py:10-65)."""

    _profile = None

    def __call__(self, phases=None):
        if phases is None:
            if self._profile is None:
                print("Warning: base profile not generated, returning `None`")
            return self._profile
        return self.calc_profile(phases)

    def init_profile(self, Nphase):
        ph = np.arange(Nphase) / Nphase
        self._profile = self.calc_profile(ph)
        self._Amax = self._profile.max()
        self._profile = self._profile / self.Amax

    def calc_profile(self, phases):
        raise NotImplementedError()

    @property
    def profile(self):
        return self._profile


class GaussProfile(GaussPortrait):
    """Sum-of-Gaussians profile; broadcast to ``Nchan`` identical channels at
    evaluation time (reference: profiles.py:68-115)."""

    def __init__(self, peak=0.5, width=0.05, amp=1):
        super().__init__(peak=peak, width=width, amp=amp)

    def set_Nchan(self, Nchan):
        raise NotImplementedError()


class UserProfile(PulseProfile):
    """Profile specified by a callable ``f(phases) -> intensity``
    (reference: profiles.py:118-153)."""

    def __init__(self, profile_func):
        self._generator = profile_func

    def calc_profile(self, phases):
        self._profile = np.asarray(self._generator(np.asarray(phases)))
        self._Amax = self._Amax if hasattr(self, "_Amax") else np.max(self._profile)
        return self._profile / self._Amax

    def calc_profiles(self, phases, Nchan=None):
        """Portrait-style evaluation: tile the 1-D profile across channels."""
        prof = self.calc_profile(phases)
        n = 1 if Nchan is None else Nchan
        return np.tile(prof, (n, 1))

    def init_profiles(self, Nphase, Nchan=None):
        ph = np.arange(Nphase) / Nphase
        self._profiles = self.calc_profiles(ph, Nchan=Nchan)
        self._Amax = self._profiles.max()
        self._profiles = self._profiles / self._Amax
        self._max_profile = self._pick_max_profile(self._profiles)


class DataProfile(DataPortrait):
    """Profile(s) from sampled data, tiled to ``Nchan`` channels when 1-D
    (reference: profiles.py:155-205)."""

    def __init__(self, profiles, phases=None, Nchan=None):
        profiles = np.array(profiles, dtype=np.float64, copy=True)
        if np.any(profiles < 0.0):
            print(
                "Warning: Some phase bins of input profile are negative, "
                "replacing them with zeros..."
            )
            profiles[profiles < 0.0] = 0.0

        self._phases = phases
        if profiles.ndim == 1:
            if Nchan is None:
                Nchan = 1
            profiles = np.tile(profiles, (Nchan, 1))

        super().__init__(profiles=profiles, phases=phases)

    def set_Nchan(self, Nchan):
        raise NotImplementedError()
