"""Pulsar emission models (reference layer: psrsigsim/pulsar/)."""

from .portraits import DataPortrait, GaussPortrait, PulsePortrait, UserPortrait
from .profiles import DataProfile, GaussProfile, PulseProfile, UserProfile
from .pulsar import Pulsar

__all__ = [
    "Pulsar",
    "PulsePortrait",
    "GaussPortrait",
    "UserPortrait",
    "DataPortrait",
    "PulseProfile",
    "GaussProfile",
    "UserProfile",
    "DataProfile",
]
