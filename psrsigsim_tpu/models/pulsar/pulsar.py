"""Pulsar: pulse-train synthesis onto a signal.

Behavioral counterpart of psrsigsim/pulsar/pulsar.py.  Host code handles
config (units, shapes, profile normalization); the actual draws run as jitted
device kernels over the full ``(Nchan, Nsamp)`` block — the reference's
``scipy.stats...rvs`` hot loops (pulsar.py:183,220,243) become single fused
XLA sample+multiply programs.

RNG: draws use explicit jax.random keys.  Pass ``seed=`` for a private,
reproducible stream, else the package-global :func:`~psrsigsim_tpu.utils.rng`
sequence is used (seed it with ``psrsigsim_tpu.utils.set_seed``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.shift import fourier_shift
from ...ops.stats import chi2_sample, normal_sample
from ...utils.quantity import make_quant
from ...utils.rng import KeySequence, default_keys
from .portraits import DataPortrait
from .profiles import GaussProfile

__all__ = ["Pulsar"]


@partial(jax.jit, static_argnames=("nsub", "df"))
def _fold_pulse_kernel(key, profiles, nsub, df, draw_norm):
    """Fold-mode synthesis: tile the portrait to nsub subints and modulate by
    chi-squared intensity draws (reference: pulsar.py:196-221).

    ``df`` is STATIC: chi2_sample routes small df to the exact gamma
    sampler and large df to Wilson-Hilferty by VALUE (ops/stats.py); a
    traced df would erase that routing.  One compile per distinct Nfold
    is the OO API's natural granularity (one per signal)."""
    block = jnp.tile(profiles, (1, nsub))
    return block * chi2_sample(key, df, block.shape) * draw_norm


@partial(jax.jit, static_argnames=("df",))
def _power_draw_kernel(key, profiles, df, draw_norm):
    """Single-pulse intensity draws over an evaluated profile block
    (reference: pulsar.py:222-244, chi2(df=1)); static ``df`` as above."""
    return profiles * chi2_sample(key, df, profiles.shape) * draw_norm


@jax.jit
def _amp_draw_kernel(key, amp_profiles):
    """Amplitude-signal synthesis: sqrt(intensity) x N(0,1)
    (reference: pulsar.py:153-183)."""
    return amp_profiles * normal_sample(key, amp_profiles.shape)


class Pulsar:
    """A pulsar: period, mean flux, pulse portrait, spectral index
    (reference: pulsar.py:11-56).

    Parameters
    ----------
    period : float
        Pulse period (sec)
    Smean : float
        Mean pulse flux density (Jy)
    profiles : PulseProfile-like, optional (default GaussProfile())
    name : str, optional
    specidx : float, optional (default 0.0)
    ref_freq : float, optional (MHz; default = signal band center)
    seed : int, optional — private reproducible RNG stream
    """

    def __init__(self, period, Smean, profiles=None, name=None, specidx=0.0,
                 ref_freq=None, seed=None):
        self._period = make_quant(period, "s")
        self._Smean = make_quant(Smean, "Jy")
        self._name = name
        self._specidx = specidx
        self._ref_freq = make_quant(ref_freq, "MHz") if ref_freq is not None else None
        self._Profiles = profiles if profiles is not None else GaussProfile()
        self._keys = KeySequence(seed) if seed is not None else default_keys

    def __repr__(self):
        namestr = "" if self.name is None else self.name + ", "
        return "Pulsar(" + namestr + "{})".format(self.period.to("ms"))

    @property
    def Profiles(self):
        return self._Profiles

    @property
    def name(self):
        return self._name

    @property
    def period(self):
        return self._period

    @property
    def Smean(self):
        return self._Smean

    @property
    def specidx(self):
        return self._specidx

    @property
    def ref_freq(self):
        return self._ref_freq

    # -- synthesis ---------------------------------------------------------
    def _nph(self, signal):
        """Phase bins per period at the signal's sample rate
        (reference: pulsar.py:124)."""
        return int((signal.samprate * self.period).decompose())

    def _add_spec_idx(self, signal):
        """Scale the portrait by ``(f/ref_freq)^specidx`` and re-wrap as a
        DataPortrait (reference: pulsar.py:86-105).  Host-side config work."""
        C = (signal.dat_freq / self.ref_freq).value ** self.specidx
        C = np.reshape(C, (signal.Nchan, 1))
        nph = self._nph(signal)
        self.Profiles.init_profiles(nph, Nchan=signal.Nchan)
        phs = np.linspace(0.0, 1.0, nph)
        full_profs = self.Profiles.calc_profiles(phs, Nchan=signal.Nchan) * C
        self._Profiles = DataPortrait(full_profs)

    def make_pulses(self, signal, tobs):
        """Generate pulses into ``signal`` for ``tobs`` seconds of observation
        (reference: pulsar.py:107-151)."""
        signal._tobs = make_quant(tobs, "s")

        if self.ref_freq is None:
            self._ref_freq = signal.fcent
        if signal.sigtype == "FilterBankSignal":
            self._add_spec_idx(signal)

        nph = self._nph(signal)
        self.Profiles.init_profiles(nph, signal.Nchan)

        if signal.sigtype in ["RFSignal", "BasebandSignal"]:
            self._make_amp_pulses(signal)
        elif signal.sigtype == "FilterBankSignal":
            self._make_pow_pulses(signal)
        else:
            raise NotImplementedError(
                "no pulse method for signal: {}".format(signal.sigtype)
            )

        # Smax feeds the radiometer noise level (reference: pulsar.py:147-151)
        pr = self.Profiles._max_profile
        nbins = len(pr)
        signal._Smax = self.Smean * nbins / float(np.sum(pr))

    def _sample_phases(self, signal):
        """Pulse phase of every sample, float64 host precision
        (reference: pulsar.py:174-176,238-240)."""
        spp = float((signal.samprate * self.period).decompose())  # samples/period
        phs = np.arange(signal.nsamp, dtype=np.float64) / spp
        return phs % 1.0

    def _make_amp_pulses(self, signal):
        """Amplitude pulses for RF/Baseband signals
        (reference: pulsar.py:153-183)."""
        signal._nsamp = int((signal.tobs * signal.samprate).decompose())
        signal.init_data(signal.nsamp)

        phs = self._sample_phases(signal)
        full_prof = np.sqrt(self.Profiles.calc_profiles(phs, Nchan=signal.Nchan))
        signal.data = _amp_draw_kernel(
            self._keys.next("pulse"), jnp.asarray(full_prof, dtype=jnp.float32)
        )

    def _make_pow_pulses(self, signal):
        """Power pulses for FilterBank signals (reference: pulsar.py:185-244)."""
        if signal.fold:
            if signal.sublen is None:
                signal._sublen = signal.tobs
                signal._nsub = 1
            else:
                signal._nsub = int(np.round((signal.tobs / signal.sublen).decompose()))

            # reference keeps _nsamp = int(nsub*period*samprate) even though
            # the data block is nsub*Nph wide (pulsar.py:206,219) — preserved
            signal._nsamp = int(
                (signal.nsub * (self.period * signal.samprate)).decompose()
            )

            signal._Nfold = float((signal.sublen / self.period).decompose())
            signal._set_draw_norm(df=signal.Nfold)

            profiles = self.Profiles.profiles_device()
            signal.data = _fold_pulse_kernel(
                self._keys.next("pulse"),
                profiles,
                signal.nsub,
                float(signal.Nfold),
                signal._draw_norm,
            )
        else:
            signal._sublen = self.period
            signal._nsub = int(np.round((signal.tobs / signal.sublen).decompose()))

            signal._Nfold = None
            signal._set_draw_norm(df=1)

            signal._nsamp = int((signal.tobs * signal.samprate).decompose())
            phs = self._sample_phases(signal)
            full_prof = self.Profiles.calc_profiles(phs, signal.Nchan)
            signal.data = _power_draw_kernel(
                self._keys.next("pulse"),
                jnp.asarray(full_prof, dtype=jnp.float32),
                1.0,
                signal._draw_norm,
            )

    # -- nulling -----------------------------------------------------------
    def null(self, signal, null_frac, length=None, frequency=None):
        """Replace a fraction of pulses with off-pulse-level noise
        (reference: pulsar.py:246-333).

        Run after ISM delays but before radiometer noise.  The reference's
        per-pulse Python loops and boolean indexing become static masks and
        ``where`` selects so the whole operation stays on device.
        """
        if length is not None or frequency is not None:
            raise NotImplementedError(
                "Length and Frequency not been implimented yet"
            )

        null_pulses = int(np.round(signal.nsub * null_frac))
        if null_pulses == 0:
            return
        nph = self._nph(signal)
        opw = self.Profiles._calcOffpulseWindow(Nphase=nph)
        df = signal.Nfold if signal.fold else 1
        if not signal.fold or signal.Nfold < 100:
            check_df = 100.0
        else:
            check_df = float(signal.Nfold)

        data_np_row0 = np.asarray(signal.data[0, :nph])
        shift_val = nph // 2 - int(np.argmax(data_np_row0))
        width = signal.data.shape[1]

        # choose pulses to null (explicit-key analog of np.random.choice)
        sel_key = self._keys.next("null_select")
        rand_pulses = np.asarray(
            jax.random.permutation(sel_key, signal.nsub)
        )[:null_pulses]

        # static column mask of nulled windows
        mask_row = np.zeros(width, dtype=bool)
        for p in rand_pulses:
            lo = nph * int(p) + shift_val
            bins = np.arange(lo, lo + nph)
            bins = bins[(bins >= 0) & (bins < width)]
            mask_row[bins] = True

        off_pulse_mean = float(np.mean(self.Profiles._max_profile[opw.astype(int)]))
        noise_key = self._keys.next("null_noise")

        if signal.delay is None:
            # same noise row across channels, as the reference's row-broadcast
            # assignment does (pulsar.py:304)
            noise_row = (
                chi2_sample(noise_key, float(df), (width,)) * signal._draw_norm
            )
            signal.data = jnp.where(
                jnp.asarray(mask_row)[None, :],
                noise_row[None, :] * off_pulse_mean,
                signal.data,
            )
        else:
            # delayed signal: build the check array, shift it per channel with
            # the accumulated delays, then replace where it lands above 1
            check_key = self._keys.next("null_noise")
            check_row = jnp.where(
                jnp.asarray(mask_row),
                chi2_sample(check_key, check_df, (width,)) * signal._draw_norm,
                0.0,
            )
            null_array = jnp.tile(check_row[None, :], (signal.Nchan, 1))
            shift_dt_ms = float((1 / signal.samprate).to("ms").value)
            delays_ms = np.asarray(
                signal.delay.to("ms").value
                if hasattr(signal.delay, "to")
                else signal.delay
            )
            shifted = fourier_shift(null_array, delays_ms, dt=shift_dt_ms)
            mask = shifted > 1
            noise = (
                chi2_sample(noise_key, float(df), signal.data.shape)
                * signal._draw_norm
            )
            signal.data = jnp.where(mask, noise * off_pulse_mean, signal.data)
