"""Pulse portraits: frequency-resolved pulse profile sets.

Behavioral counterpart of psrsigsim/pulsar/portraits.py.  Portraits are
*config-time* objects: construction and normalization run on host (numpy /
float64, matching the reference numerically), while evaluation offers both a
host path (``calc_profiles``) and a device path (``profiles_device`` /
``eval_device``) that jitted pipelines consume.

A portrait is an INTENSITY series even for amplitude-style signals.
"""

from __future__ import annotations

import numpy as np

from ...ops.interp import PchipCoeffs, pchip_eval_np, pchip_fit_np
from ...ops.window import offpulse_window

__all__ = ["PulsePortrait", "GaussPortrait", "DataPortrait", "UserPortrait"]


class PulsePortrait:
    """Base class: a set of profiles across the band
    (reference: portraits.py:9-91)."""

    _profiles = None

    def __call__(self, phases=None):
        if phases is None:
            if self._profiles is None:
                print("Warning: base profiles not generated, returning `None`")
            return self._profiles
        return self.calc_profiles(phases)

    def init_profiles(self, Nphase, Nchan=None):
        """Evaluate on an even grid and normalize by the global max
        (reference: portraits.py:32-45)."""
        ph = np.arange(Nphase) / Nphase
        self._profiles = self.calc_profiles(ph, Nchan=Nchan)
        self._Amax = self._profiles.max()
        self._profiles = self._profiles / self.Amax
        self._max_profile = self._pick_max_profile(self._profiles)

    @staticmethod
    def _pick_max_profile(profiles):
        """The first channel achieving the global maximum — the reference
        selects the row with ``pr.max() == 1.0`` (portraits.py:45)."""
        row = int(np.argmax(profiles.max(axis=1)))
        return profiles[row]

    def calc_profiles(self, phases, Nchan=None):
        raise NotImplementedError()

    def _calcOffpulseWindow(self, Nphase=None):
        """Off-pulse window of the peak profile (PyPulse-derived; reference:
        portraits.py:62-82).  Delegates to the exact host op."""
        return offpulse_window(self._max_profile, Nphase)

    @property
    def profiles(self):
        return self._profiles

    @property
    def Amax(self):
        return self._Amax

    # -- device views -------------------------------------------------------
    def profiles_device(self):
        """Normalized profile block ``(Nchan, Nphase)`` as a device array."""
        import jax.numpy as jnp

        if self._profiles is None:
            raise ValueError("run init_profiles first")
        return jnp.asarray(np.asarray(self._profiles, dtype=np.float32))


class GaussPortrait(PulsePortrait):
    """Sum-of-Gaussians portrait (reference: portraits.py:94-198).

    Component params may be scalars (single Gaussian, tiled across channels),
    1-D arrays (multi-component profile, tiled), or 2-D arrays
    ``(Nchan, Ncomp)`` — which the reference collapses to a single summed
    profile tiled to all channels (kept; DIVERGENCES.md #8).
    """

    def __init__(self, peak=0.5, width=0.05, amp=1):
        self._peak = peak
        self._width = width
        self._amp = amp
        self._profiles = None

    def init_profiles(self, Nphase, Nchan=None):
        # the Gauss override does NOT renormalize again — calc_profiles
        # already divides by the cached Amax (reference: portraits.py:131-140)
        ph = np.arange(Nphase) / Nphase
        self._profiles = self.calc_profiles(ph, Nchan=Nchan)
        self._max_profile = self._pick_max_profile(self._profiles)

    def calc_profiles(self, phases, Nchan=None):
        ph = np.asarray(phases, dtype=np.float64)
        peak = self._peak
        if hasattr(peak, "ndim") and getattr(peak, "ndim", 0) >= 1:
            peak = np.asarray(peak)
            width = np.asarray(self._width)
            amp = np.asarray(self._amp)
            if peak.ndim == 1:
                if Nchan is None:
                    raise ValueError(
                        "Nchan must be provided if only 1-dim profile "
                        "information provided."
                    )
                profile = _gaussian_mult_1d(ph, peak, width, amp)
                profiles = np.tile(profile, (Nchan, 1))
            elif peak.ndim == 2:
                nchan = peak.shape[0]
                profiles = _gaussian_mult_2d(ph, peak, width, amp, nchan)
            else:
                raise ValueError("peak array must be 1-D or 2-D")
        else:
            if Nchan is None:
                raise ValueError(
                    "Nchan must be provided if only 1-dim profile "
                    "information provided."
                )
            profile = _gaussian_sing_1d(ph, peak, self._width, self._amp)
            profiles = np.tile(profile, (Nchan, 1))

        # Amax cached on first evaluation and reused (reference:
        # portraits.py:177) so repeated calls share one normalization
        self._Amax = self._Amax if hasattr(self, "_Amax") else np.amax(profiles)
        return profiles / self._Amax

    @property
    def peak(self):
        return self._peak

    @property
    def width(self):
        return self._width

    @property
    def amp(self):
        return self._amp


class DataPortrait(PulsePortrait):
    """Portrait interpolated from sampled profile data via PCHIP
    (reference: portraits.py:200-267)."""

    def __init__(self, profiles, phases=None):
        profiles = np.array(profiles, dtype=np.float64, copy=True)
        if np.any(profiles < 0.0):
            print(
                "Warning: Some phase bins of input profile are negative, "
                "replacing them with zeros..."
            )
            profiles[profiles < 0.0] = 0.0

        if phases is None:
            n = profiles.shape[1]
            if np.any(profiles[:, 0] != profiles[:, -1]):
                # enforce periodicity
                profiles = np.append(profiles, profiles[:, :1], axis=1)
                phases = np.arange(n + 1) / n
            else:
                phases = np.arange(n) / n
        else:
            phases = np.asarray(phases, dtype=np.float64)
            if phases[-1] != 1:
                phases = np.append(phases, 1)
                profiles = np.append(profiles, profiles[:, :1], axis=1)
            elif np.any(profiles[:, 0] != profiles[:, -1]):
                profiles[:, -1] = profiles[:, 0]

        self._phases_grid = phases
        self._profile_data = profiles
        self._coeffs = pchip_fit_np(phases, profiles)

    def calc_profiles(self, phases, Nchan=None):
        profiles = pchip_eval_np(self._coeffs, np.asarray(phases))
        # no Amax caching here — each call normalizes by its own max unless
        # init_profiles set one (reference: portraits.py:266)
        amax = self._Amax if hasattr(self, "_Amax") else np.max(profiles)
        return profiles / amax

    def coeffs_device(self):
        """PCHIP coefficients pytree (float32 device arrays) for in-jit
        evaluation via :func:`psrsigsim_tpu.ops.pchip_eval`."""
        import jax.numpy as jnp

        return PchipCoeffs(
            x=jnp.asarray(self._coeffs.x, dtype=jnp.float32),
            y=jnp.asarray(self._coeffs.y, dtype=jnp.float32),
            d=jnp.asarray(self._coeffs.d, dtype=jnp.float32),
        )


class UserPortrait(PulsePortrait):
    """User-specified 2-D portrait from a callable (stub in the
    reference, portraits.py:270-275; completed here like the 1-D
    ``UserProfile`` the reference does implement, profiles.py:118-153).

    ``portrait_func(phases, Nchan) -> (Nchan, Nphase)`` evaluates the
    frequency-resolved intensity at the given phases (in [0, 1)); the
    base-class normalization (global max across all channels,
    reference portraits.py:32-45) applies on top.
    """

    def __init__(self, portrait_func):
        if not callable(portrait_func):
            raise TypeError("UserPortrait takes a callable "
                            "portrait_func(phases, Nchan)")
        self._generator = portrait_func

    def init_profiles(self, Nphase, Nchan=None):
        # like GaussPortrait's override: calc_profiles already divides by
        # the cached Amax, so no second normalization (which would reset
        # _Amax to 1 and break later direct calc_profiles calls).
        # The normalizer is pinned from a DENSE grid here (>= 2048 bins)
        # so a later sparse-grid call can never cache a peak-missing Amax.
        self._ensure_amax(max(int(Nphase), 2048), Nchan)
        ph = np.arange(Nphase) / Nphase
        self._profiles = self.calc_profiles(ph, Nchan=Nchan)
        self._max_profile = self._pick_max_profile(self._profiles)

    def _ensure_amax(self, ndense, Nchan):
        if hasattr(self, "_Amax"):
            return
        ph = np.arange(ndense) / ndense
        n = 1 if Nchan is None else int(Nchan)
        out = np.asarray(self._generator(ph, n), dtype=np.float64)
        amax = float(np.amax(out))
        if not (np.isfinite(amax) and amax > 0):
            raise ValueError(
                f"portrait_func's maximum over a {ndense}-bin phase grid "
                f"is {amax}; the portrait must be positive somewhere to "
                "define the normalization")
        self._Amax = amax

    def calc_profiles(self, phases, Nchan=None):
        ph = np.asarray(phases, dtype=np.float64)
        if np.any(ph > 1) or np.any(ph < 0):
            raise ValueError("Phase values must all lie within [0,1].")
        n = 1 if Nchan is None else int(Nchan)
        out = np.asarray(self._generator(ph, n), dtype=np.float64)
        if out.shape != (n, len(ph)):
            raise ValueError(
                f"portrait_func returned shape {out.shape}, expected "
                f"({n}, {len(ph)})")
        # Amax cached once and reused, like GaussPortrait (reference:
        # portraits.py:177): synthesis paths call calc_profiles directly
        # and rely on max ~ 1 for Smax/noise scales.  Cached from a dense
        # evaluation (never this call's possibly-sparse grid), and
        # validated > 0 — an all-zero first draw must not pin Amax=0
        # (advisor round 3).
        self._ensure_amax(max(len(ph), 2048), Nchan)
        return out / self._Amax


def _gaussian_sing_1d(phases, peak, width, amp):
    if np.any(phases > 1) or np.any(phases < 0):
        raise ValueError("Phase values must all lie within [0,1].")
    return amp * np.exp(-0.5 * ((phases - peak) / width) ** 2)


def _gaussian_mult_1d(phases, peaks, widths, amps):
    if np.any(phases > 1) or np.any(phases < 0):
        raise ValueError("Phase values must all lie within [0,1].")
    comps = amps[:, None] * np.exp(
        -0.5 * ((phases[None, :] - peaks[:, None]) / widths[:, None]) ** 2
    )
    return comps.sum(axis=0)


def _gaussian_mult_2d(phases, peaks, widths, amps, nchan):
    # reference tiles the SAME summed profile to every channel
    # (portraits.py:293-296); kept for parity (DIVERGENCES.md #8)
    return np.array(
        [_gaussian_mult_1d(phases, peaks[:], widths[:], amps[:]) for _ in range(nchan)]
    )
