"""Interstellar-medium propagation effects.

Behavioral counterpart of psrsigsim/ism/ism.py.  Every per-channel serial
shift loop in the reference (disperse :57-60, FD_shift :136-139,
scatter_broaden :203-206) becomes ONE batched Fourier-shift op over the whole
``(Nchan, Nsamp)`` block; coherent baseband dedispersion applies the L&K
transfer function to all polarization channels in one batched FFT.
"""

from __future__ import annotations

import numpy as np

from ...ops.convolve import convolve_profiles as _convolve_profiles_op
from ...ops.shift import coherent_dedisperse, fourier_shift
from ...utils.constants import DM_K, KOLMOGOROV_BETA
from ...utils.quantity import Quantity, make_quant
from ..pulsar.portraits import DataPortrait

__all__ = ["ISM", "fd_delays_ms", "scatter_delays_ms"]


def fd_delays_ms(freqs_mhz, fd_params_s):
    """Per-channel FD-polynomial delays in ms:
    ``sum_i c_i ln(f/1GHz)^(i+1)`` with coefficients in seconds
    (Arzoumanian et al. 2016; reference: ism/ism.py:100-156).

    Pure host function — the delay vector feeds the batched Fourier shift
    (either :meth:`ISM.FD_shift` or an in-graph pipeline stage)."""
    freqs_mhz = np.asarray(freqs_mhz, dtype=np.float64)
    log_ratio = np.log(freqs_mhz / 1000.0)
    delays_ms = np.zeros_like(freqs_mhz)
    for ii, c in enumerate(fd_params_s):
        delays_ms += 1e3 * float(c) * log_ratio ** (ii + 1)
    return delays_ms


def _tau_d_exponent(beta):
    """Scattering-scaling exponent (thin/thick screen branches; reference:
    ism/ism.py:340-358)."""
    if beta < 4:
        return -2.0 * beta / (beta - 2)
    if beta > 4:
        return -8.0 / (6 - beta)
    raise ValueError("beta == 4 is a degenerate scaling (reference leaves "
                     "it undefined); use beta < 4 or beta > 4")


def scatter_delays_ms(freqs_mhz, tau_d_s, ref_freq_mhz, beta=KOLMOGOROV_BETA):
    """Per-channel scatter-broadening delays in ms: tau_d scaled from
    ``ref_freq`` to each channel by the thin/thick-screen law
    (reference: ism/ism.py:158-220,340-358).  Pure host function."""
    freqs_mhz = np.asarray(freqs_mhz, dtype=np.float64)
    exp = _tau_d_exponent(beta)
    return 1e3 * float(tau_d_s) * (freqs_mhz / float(ref_freq_mhz)) ** exp


class ISM:
    """Class for modeling interstellar medium effects on pulsar signals
    (reference: ism/ism.py:12-18)."""

    def __init__(self):
        pass

    # -- dispersion --------------------------------------------------------
    def disperse(self, signal, dm):
        r"""Disperse the signal: :math:`\Delta t_{\rm DM} = k_{\rm DM}\,
        {\rm DM}/\nu^2` per channel (reference: ism/ism.py:20-38).

        Raises ValueError if the signal was already dispersed.
        """
        signal._dm = make_quant(dm, "pc/cm^3")

        if getattr(signal, "_dispersed", False):
            raise ValueError("Signal has already been dispersed!")

        if signal.sigtype == "FilterBankSignal":
            self._disperse_filterbank(signal, signal._dm)
        elif signal.sigtype == "BasebandSignal":
            self._disperse_baseband(signal, signal._dm)

        signal._dispersed = True

    def _disperse_filterbank(self, signal, dm):
        """One batched phase-ramp shift instead of the reference's serial
        per-channel loop (ism/ism.py:40-74)."""
        freq_array = signal.dat_freq
        time_delays = (DM_K * dm * np.power(freq_array, -2)).to("ms")
        signal.delay = (
            time_delays if signal.delay is None else signal.delay + time_delays
        )
        shift_dt = (1 / signal.samprate).to("ms")
        signal.data = fourier_shift(
            signal.data, time_delays.value, dt=float(shift_dt.value)
        )

    def _disperse_baseband(self, signal, dm):
        """Coherent dispersion via the L&K eq 5.21 transfer function, all
        channels in one batched FFT (reference: ism/ism.py:76-98)."""
        dt_us = float((1 / signal.samprate).to("us").value)
        signal.data = coherent_dedisperse(
            signal.data,
            float(dm.value),
            float(signal.fcent.to("MHz").value),
            float(signal.bw.to("MHz").value),
            dt_us,
        )

    # -- frequency-dependent (FD) shift ------------------------------------
    def FD_shift(self, signal, FD_params):
        r"""Shift profiles by the NANOGrav FD-parameter delay polynomial
        :math:`\Delta t_{\rm FD} = \sum_i c_i \ln(\nu/1\,{\rm GHz})^i`
        (Arzoumanian et al. 2016; reference: ism/ism.py:100-156).

        FD params are in seconds; delays applied in ms.
        """
        freq_array = signal.dat_freq
        delays_ms = fd_delays_ms(
            freq_array.to("MHz").value,
            [make_quant(c, "s").to("s").value for c in FD_params],
        )
        time_delays = Quantity(delays_ms, "ms")

        signal.delay = (
            time_delays if signal.delay is None else signal.delay + time_delays
        )
        shift_dt = (1 / signal.samprate).to("ms")
        signal.data = fourier_shift(signal.data, delays_ms, dt=float(shift_dt.value))
        signal._FDshifted = True

    # -- scattering --------------------------------------------------------
    def scatter_broaden(self, signal, tau_d, ref_freq, beta=KOLMOGOROV_BETA,
                        convolve=False, pulsar=None):
        """Scatter-broadening delays, either as direct per-channel time shifts
        or by convolving exponential scattering tails into the pulse profiles
        BEFORE ``make_pulses`` (reference: ism/ism.py:158-240).

        Parameters mirror the reference: tau_d [s], ref_freq [MHz], beta
        (scaling law), convolve flag, pulsar (required when convolve=True).
        """
        freq_array = signal.dat_freq
        ref_freq = make_quant(ref_freq, "MHz")
        tau_d = make_quant(tau_d, "s").to("ms")
        tau_d_scaled = self.scale_tau_d(tau_d, ref_freq, freq_array, beta=beta)

        if not convolve:
            signal.delay = (
                tau_d_scaled if signal.delay is None else signal.delay + tau_d_scaled
            )
            shift_dt = (1 / signal.samprate).to("ms")
            signal.data = fourier_shift(
                signal.data, tau_d_scaled.value, dt=float(shift_dt.value)
            )
        else:
            nph = int((signal.samprate * pulsar.period).decompose())
            pulsar.Profiles.init_profiles(nph, signal.Nchan)
            phs = np.linspace(0.0, 1.0, nph)
            full_profs = pulsar.Profiles.calc_profiles(phs, signal.Nchan)
            # exponential scattering tails, one per channel
            t = np.linspace(0, float(pulsar.period.to("ms").value), nph)
            tails = np.exp(-t[None, :] / tau_d_scaled.value[:, None])
            convolved = self.convolve_profile(full_profs, tails, width=nph)
            pulsar._Profiles = DataPortrait(convolved)

    def convolve_profile(self, profiles, convolve_array, width=2048):
        """Flux-preserving FFT convolution of kernels into profiles
        (reference: ism/ism.py:243-288).  Returns the convolved array; does
        NOT reassign any pulsar's profiles.  Host float64."""
        profiles = np.asarray(profiles, dtype=np.float64)
        kernels = np.asarray(convolve_array, dtype=np.float64)
        psum = profiles.sum(axis=-1, keepdims=True)
        ksum = kernels.sum(axis=-1, keepdims=True)
        # sum-normalize with a zero-sum guard (divide by 1 leaves row as-is)
        pnorm = profiles / np.where(psum == 0.0, 1.0, psum)
        knorm = kernels / np.where(ksum == 0.0, 1.0, ksum)
        nfft = pnorm.shape[-1] + knorm.shape[-1] - 1
        conv = np.fft.irfft(
            np.fft.rfft(pnorm, n=nfft, axis=-1) * np.fft.rfft(knorm, n=nfft, axis=-1),
            n=nfft,
            axis=-1,
        )
        return psum * conv[..., :width]

    def convolve_profile_device(self, profiles, convolve_array, width=2048):
        """Device/jit variant of :meth:`convolve_profile` (float32) for
        in-graph ensembles with per-observation scattering."""
        return _convolve_profiles_op(profiles, convolve_array, width)

    # -- scintillation scaling laws (Michael Lam 2017; Stinebring & Condon
    #    1990 for the beta branches; reference: ism/ism.py:300-358) ---------
    @staticmethod
    def _beta_exponent(beta, thin, thick):
        if beta < 4:
            return thin(beta)
        if beta > 4:
            return thick(beta)
        raise ValueError("beta == 4 is a degenerate scaling (reference leaves "
                         "it undefined); use beta < 4 or beta > 4")

    def scale_dnu_d(self, dnu_d, nu_i, nu_f, beta=KOLMOGOROV_BETA):
        """Scintillation bandwidth scaling: dnu_d ∝ nu^(2β/(β-2)) (thin
        screen) (reference: ism/ism.py:300-318)."""
        exp = self._beta_exponent(
            beta, lambda b: 2.0 * b / (b - 2), lambda b: 8.0 / (6 - b)
        )
        return dnu_d * (nu_f / nu_i) ** exp

    def scale_dt_d(self, dt_d, nu_i, nu_f, beta=KOLMOGOROV_BETA):
        """Scintillation timescale scaling: dt_d ∝ nu^(2/(β-2)) (thin screen)
        (reference: ism/ism.py:320-338)."""
        exp = self._beta_exponent(
            beta, lambda b: 2.0 / (b - 2), lambda b: float(b - 2) / (6 - b)
        )
        return dt_d * (nu_f / nu_i) ** exp

    def scale_tau_d(self, tau_d, nu_i, nu_f, beta=KOLMOGOROV_BETA):
        """Scattering timescale scaling: tau_d ∝ nu^(-2β/(β-2)) (thin screen)
        (reference: ism/ism.py:340-358)."""
        return tau_d * (nu_f / nu_i) ** _tau_d_exponent(beta)
