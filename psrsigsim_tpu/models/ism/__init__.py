"""ISM propagation models (reference layer: psrsigsim/ism/)."""

from .ism import ISM, fd_delays_ms, scatter_delays_ms

__all__ = ["ISM", "fd_delays_ms", "scatter_delays_ms"]
