"""ISM propagation models (reference layer: psrsigsim/ism/)."""

from .ism import ISM

__all__ = ["ISM"]
