"""Domain models: pulsar emission, ISM propagation, telescope observation."""

from . import pulsar

__all__ = ["pulsar"]
