"""Domain models: pulsar emission, ISM propagation, telescope observation."""

from . import ism, pulsar, telescope

__all__ = ["pulsar", "ism", "telescope"]
