"""Signal data model: pytree state + reference-parity signal classes
(reference layer: psrsigsim/signal/)."""

from .signals import (
    BasebandSignal,
    BaseSignal,
    FilterBankSignal,
    RFSignal,
    Signal,
)
from .state import FLOAT32, INT8, SignalMeta, SignalState, empty_state

__all__ = [
    "Signal",
    "BaseSignal",
    "RFSignal",
    "BasebandSignal",
    "FilterBankSignal",
    "SignalMeta",
    "SignalState",
    "empty_state",
    "FLOAT32",
    "INT8",
]
