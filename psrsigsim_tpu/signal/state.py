"""Signal state as a JAX pytree + static metadata.

The reference's data model is a mutable object whose ``._data`` every pipeline
stage overwrites in place, with hidden state flags accumulating on the side
(`_delay`, `_dispersed`, `_Smax`; see SURVEY.md §1).  That shape is hostile to
XLA, so the TPU-native core splits it:

* :class:`SignalState` — the dynamic leaves (sample data, accumulated delay)
  that flow through jit/vmap/pjit as one pytree.
* :class:`SignalMeta` — frozen, hashable trace-time constants (band geometry,
  sampling, fold config, dtype tag).  Shapes derive from these on host,
  so everything under jit is static-shaped.

The user-facing classes in :mod:`psrsigsim_tpu.signal.signals` are thin
mutable shells over these for reference API parity.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["SignalMeta", "SignalState", "FLOAT32", "INT8"]

# dtype tags kept as strings so SignalMeta stays hashable
FLOAT32 = "float32"
INT8 = "int8"


@dataclasses.dataclass(frozen=True)
class SignalMeta:
    """Static signal configuration (hashable; safe as a jit static arg).

    Canonical units: MHz for frequencies/rates, seconds for durations.
    Mirrors the metadata surface of the reference's BaseSignal/
    FilterBankSignal (signal/signal.py:43-71, signal/fb_signal.py:64-112).
    """

    sigtype: str  # "FilterBankSignal" | "BasebandSignal" | "RFSignal"
    fcent_mhz: float
    bw_mhz: float
    samprate_mhz: float
    nchan: int
    npols: int = 1
    dtype: str = FLOAT32
    fold: bool = True
    sublen_s: Optional[float] = None

    # ---- derived, host-side ----
    def dat_freq_mhz(self):
        """Channel center grid: ``arange(fcent-bw/2, fcent+bw/2, bw/nchan)``
        (reference: fb_signal.py:101-106)."""
        first = self.fcent_mhz - self.bw_mhz / 2
        last = self.fcent_mhz + self.bw_mhz / 2
        step = self.bw_mhz / self.nchan
        return np.arange(first, last, step)

    def nsamp_for(self, tobs_s):
        """Samples per channel for an observation of ``tobs_s`` seconds."""
        return int(tobs_s * self.samprate_mhz * 1e6)

    @property
    def np_dtype(self):
        return np.int8 if self.dtype == INT8 else np.float32


@jax.tree_util.register_pytree_node_class
class SignalState:
    """Dynamic signal contents: ``data (..., Nchan, Nsamp)`` and the
    accumulated per-channel ``delay_ms (..., Nchan)`` (None before any
    propagation stage; the reference accumulates the same way,
    ism/ism.py:44-47,123-126,190-193)."""

    __slots__ = ("data", "delay_ms")

    def __init__(self, data, delay_ms=None):
        self.data = data
        self.delay_ms = delay_ms

    def tree_flatten(self):
        return (self.data, self.delay_ms), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def replace(self, **kw):
        return SignalState(
            data=kw.get("data", self.data),
            delay_ms=kw.get("delay_ms", self.delay_ms),
        )

    def add_delay(self, delay_ms):
        """Accumulate a per-channel delay vector (ms)."""
        new = delay_ms if self.delay_ms is None else self.delay_ms + delay_ms
        return self.replace(delay_ms=new)

    def __repr__(self):
        shape = getattr(self.data, "shape", None)
        return f"SignalState(data{shape}, delay={'set' if self.delay_ms is not None else 'None'})"


def empty_state(meta, nsamp):
    """Allocate a zeroed device buffer for ``(Nchan, nsamp)``."""
    return SignalState(data=jnp.zeros((meta.nchan, nsamp), dtype=jnp.float32))
