"""User-facing signal classes with reference API parity.

These are host-side shells: they hold a :class:`SignalMeta`, a
:class:`SignalState` (device arrays), and the bookkeeping flags the reference
scatters across private attributes.  All heavy math happens in
:mod:`psrsigsim_tpu.ops` / the model layer; these classes only orchestrate.

API mirrors psrsigsim/signal/ (signal.py, fb_signal.py, bb_signal.py,
rf_signal.py) so reference users can port scripts unchanged.
"""

from __future__ import annotations

import numpy as np

from ..ops.stats import chi2_draw_norm
from ..utils.quantity import Quantity, make_quant
from .state import FLOAT32, INT8, SignalMeta, SignalState

__all__ = ["BaseSignal", "Signal", "FilterBankSignal", "BasebandSignal", "RFSignal"]

_DTYPE_TAGS = {
    np.float32: FLOAT32,
    "float32": FLOAT32,
    np.int8: INT8,
    "int8": INT8,
}


def _dtype_tag(dtype):
    """Validate and normalize the dtype argument.

    The reference's check (signal/signal.py:56) was an always-true no-op; we
    enforce the intended {float32, int8} set (DIVERGENCES.md #1).
    """
    try:
        hashable = dtype if isinstance(dtype, (str, type)) else np.dtype(dtype).type
    except TypeError:
        hashable = None
    if hashable in _DTYPE_TAGS:
        return _DTYPE_TAGS[hashable]
    raise ValueError(f"data type {dtype!r} not supported")


class BaseSignal:
    """Base class for signals (reference: signal/signal.py:11-165).

    Required Args:
        fcent [float]: central radio frequency (MHz)
        bandwidth [float]: radio bandwidth of signal (MHz)
    """

    _sigtype = "Signal"

    def __init__(self, fcent, bandwidth, sample_rate=None, dtype=np.float32,
                 Npols=1):
        self._fcent = make_quant(fcent, "MHz")
        bw = make_quant(bandwidth, "MHz")
        self._bw = abs(bw) if bw.value < 0 else bw
        self._samprate = (
            make_quant(sample_rate, "MHz") if sample_rate is not None else None
        )
        self._dtype_tag = _dtype_tag(dtype)
        if Npols != 1:
            raise ValueError("Only total intensity polarization is currently supported")
        self._Npols = 1

        self._state = None
        self._delay = None
        self._dm = None
        self._tobs = None
        self._nsamp = None
        self._Nchan = None
        self._draw_max = None
        self._draw_norm = 1

    # -- data management ----------------------------------------------------
    def init_data(self, Nsamp):
        """Allocate a zeroed ``(Nchan, Nsamp)`` device buffer
        (reference: signal/signal.py:87-94 uses np.empty; zeros are safer)."""
        import jax.numpy as jnp

        self._nsamp = int(Nsamp)
        self._state = SignalState(
            data=jnp.zeros((self.Nchan, self._nsamp), dtype=jnp.float32)
        )

    @property
    def state(self):
        """The underlying :class:`SignalState` pytree (device arrays)."""
        return self._state

    @state.setter
    def state(self, new_state):
        self._state = new_state

    def meta(self, fold=False, sublen_s=None):
        """Build the static :class:`SignalMeta` for functional pipelines."""
        return SignalMeta(
            sigtype=self.sigtype,
            fcent_mhz=float(self._fcent.to("MHz").value),
            bw_mhz=float(self._bw.to("MHz").value),
            samprate_mhz=float(self._samprate.to("MHz").value),
            nchan=int(self.Nchan),
            npols=self._Npols,
            dtype=self._dtype_tag,
            fold=fold,
            sublen_s=sublen_s,
        )

    # -- reference-parity surface ------------------------------------------
    def __repr__(self):
        return f"{self.sigtype}({self.fcent}, bw={self.bw})"

    def __add__(self, b):
        """overload ``+`` to concatenate signals"""
        raise NotImplementedError()

    def _set_draw_norm(self):
        raise NotImplementedError()

    def to_RF(self):
        raise NotImplementedError()

    def to_Baseband(self):
        raise NotImplementedError()

    def to_FilterBank(self, Nsubband=512):
        raise NotImplementedError()

    @property
    def data(self):
        return self._state.data if self._state is not None else None

    @data.setter
    def data(self, value):
        if self._state is None:
            self._state = SignalState(data=value)
        else:
            self._state = self._state.replace(data=value)

    @property
    def sigtype(self):
        return self._sigtype

    @property
    def Nchan(self):
        return self._Nchan

    @property
    def fcent(self):
        return self._fcent

    @property
    def bw(self):
        return self._bw

    @property
    def tobs(self):
        return self._tobs

    @property
    def samprate(self):
        return self._samprate

    @property
    def nsamp(self):
        return self._nsamp

    @property
    def dtype(self):
        return np.int8 if self._dtype_tag == INT8 else np.float32

    @property
    def Npols(self):
        return self._Npols

    @property
    def dat_freq(self):
        return self._dat_freq

    @property
    def delay(self):
        return self._delay

    @delay.setter
    def delay(self, value):
        self._delay = value

    @property
    def dm(self):
        return self._dm

    @property
    def DM(self):
        return self._dm


def Signal():
    """helper function to instantiate signals (reference stub,
    signal/signal.py:168-171)"""
    raise NotImplementedError()


class FilterBankSignal(BaseSignal):
    """2-D intensity signal ``(Nchan, Nsamp)``; fold vs single-pulse modes
    (reference: signal/fb_signal.py:11-161).

    Optional Args:
        Nsubband [int]: number of sub-bands, default 512
        sample_rate [float]: MHz; default 1/(20.48 us) — the coherently-
            dedispersed XUPPI rate
        sublen [float]: subintegration length (s) in fold mode
        fold [bool]: folded subintegrations (True) or single pulses (False)
    """

    _sigtype = "FilterBankSignal"

    def __init__(self, fcent, bandwidth, Nsubband=512, sample_rate=None,
                 sublen=None, dtype=np.float32, fold=True):
        super().__init__(fcent, bandwidth, sample_rate=sample_rate,
                         dtype=dtype, Npols=1)
        self._fold = bool(fold)
        self._sublen = None if sublen is None else make_quant(sublen, "s")
        self._Nfold = None
        self._nsub = None

        if self._samprate is None:
            self._samprate = (1 / make_quant(20.48, "us")).to("MHz")
        else:
            f_nyquist = 2 * self._bw
            if self._samprate < f_nyquist:
                print(
                    "Warning: specified sample rate {} < Nyquist frequency {}".format(
                        self._samprate, f_nyquist
                    )
                )

        self._Nchan = int(Nsubband)
        first = (self._fcent - self._bw / 2).to("MHz").value
        last = (self._fcent + self._bw / 2).to("MHz").value
        step = (self._bw / self._Nchan).to("MHz").value
        self._dat_freq = Quantity(np.arange(first, last, step), "MHz")

        self._set_draw_norm()

    def _set_draw_norm(self, df=1):
        """Dynamic-range scaling for the intensity draws
        (reference: fb_signal.py:114-121).

        Note on int8: like the reference — whose ``_make_pow_pulses`` rebinds
        ``_data`` to the float draw product (pulsar.py:220,243) — the live
        signal buffer stays floating point; ``dtype=int8`` selects the
        draw-norm/clip dynamic range, and quantization happens at
        ``Telescope.observe`` / export time.
        """
        self._draw_max, self._draw_norm = chi2_draw_norm(self.dtype, df)

    @property
    def fold(self):
        return self._fold

    @property
    def sublen(self):
        return self._sublen

    @property
    def Nfold(self):
        return self._Nfold

    @property
    def nsub(self):
        return self._nsub

    def meta(self):
        return SignalMeta(
            sigtype=self.sigtype,
            fcent_mhz=float(self._fcent.to("MHz").value),
            bw_mhz=float(self._bw.to("MHz").value),
            samprate_mhz=float(self._samprate.to("MHz").value),
            nchan=self._Nchan,
            npols=self._Npols,
            dtype=self._dtype_tag,
            fold=self._fold,
            sublen_s=(
                float(self._sublen.to("s").value) if self._sublen is not None else None
            ),
        )

    def to_RF(self):
        raise NotImplementedError()

    def to_Baseband(self):
        raise NotImplementedError()

    def to_FilterBank(self, Nsubband=512):
        return self


class BasebandSignal(BaseSignal):
    """Complex-band time-domain signal, 0 Hz → bw; Nyquist default sampling;
    ``Nchan`` polarization channels (reference: signal/bb_signal.py:9-77)."""

    _sigtype = "BasebandSignal"

    def __init__(self, fcent, bandwidth, sample_rate=None, dtype=np.float32,
                 Nchan=2):
        super().__init__(fcent, bandwidth, sample_rate=sample_rate,
                         dtype=dtype, Npols=1)
        self._Nchan = int(Nchan)
        self._dat_freq = Quantity(
            np.full(self._Nchan, self._fcent.to("MHz").value), "MHz"
        )

        f_nyquist = 2 * self._bw
        if self._samprate is None:
            self._samprate = f_nyquist.to("MHz")
        elif self._samprate < f_nyquist:
            print(
                "Warning: specified sample rate {} < Nyquist frequency {}".format(
                    self._samprate, f_nyquist
                )
            )

    def to_RF(self):
        raise NotImplementedError()

    def to_Baseband(self):
        return self

    def to_FilterBank(self, Nsubband=512):
        """Channelize the baseband stream into a SEARCH-mode filterbank
        (stub in the reference, signal/bb_signal.py:58-76; implemented
        here as the critically-sampled FFT filterbank real backends run
        — :func:`psrsigsim_tpu.ops.channelize_power`, one batched rFFT
        over all frames and polarizations).

        Requires data (synthesize with ``Pulsar.make_pulses`` first).
        Returns a new :class:`FilterBankSignal` with ``Nsubband``
        channels, sample spacing ``2*Nsubband/samprate``, and the
        detected AA+BB intensity; the baseband signal is unchanged.
        """
        if self._state is None or self._state.data is None:
            raise ValueError(
                "no baseband data to channelize; run make_pulses first")
        from ..ops.channelize import channelize_power

        nchan = int(Nsubband)
        frame = 2 * nchan
        nsamp_in = int(self._state.data.shape[-1])
        if nsamp_in < frame:
            raise ValueError(
                f"need at least one frame of 2*Nsubband={frame} samples; "
                f"have {nsamp_in}")
        power = channelize_power(self._state.data, nchan)
        nframes = int(power.shape[1])
        samprate_in = float(self._samprate.to("MHz").value)
        # constructed without sample_rate (then overridden) so the
        # full-band Nyquist warning — meant for user-specified rates —
        # does not fire on every conversion: the detected stream is
        # critically sampled per channel by construction
        out = FilterBankSignal(
            float(self._fcent.to("MHz").value),
            float(self._bw.to("MHz").value),
            Nsubband=nchan,
            fold=False,
            dtype=np.float32,
        )
        out._samprate = make_quant(samprate_in / frame, "MHz")
        out.data = power
        out._nsamp = nframes
        # tobs reflects the frames actually covered (a partial trailing
        # frame is dropped by the framing)
        out._tobs = make_quant(nframes * frame / (samprate_in * 1e6), "s")
        # observe()/radiometer bookkeeping: one "subint" spanning the
        # stream (matching the sublen=None SEARCH convention) and the
        # source signal's flux scale
        out._nsub = 1
        out._sublen = out._tobs
        if getattr(self, "_Smax", None) is not None:
            out._Smax = self._Smax
        if self.dm is not None:
            out._dm = self.dm
        return out


class RFSignal(BaseSignal):
    """True radio-frequency sampled time series (reference:
    signal/rf_signal.py:9-87).  Mostly a memory-hungry stub upstream; kept
    for API parity."""

    _sigtype = "RFSignal"

    def __init__(self, fcent, bandwidth, sample_rate=None, dtype=np.float32):
        super().__init__(fcent, bandwidth, sample_rate=sample_rate,
                         dtype=dtype, Npols=1)
        self._Nchan = 2
        self._dat_freq = Quantity(
            np.full(self._Nchan, self._fcent.to("MHz").value), "MHz"
        )

        f_nyquist = 2 * (self._fcent + self._bw / 2)
        if self._samprate is None:
            self._samprate = f_nyquist.to("MHz")
        elif self._samprate < f_nyquist:
            print(
                "Warning: specified sample rate {} < Nyquist frequency {}".format(
                    self._samprate, f_nyquist
                )
            )

    def to_RF(self):
        return self

    def to_Baseband(self):
        raise NotImplementedError()

    def to_FilterBank(self, Nsubband=512):
        raise NotImplementedError()
